"""Sharded host plane suite: the consistent-hash event-store router must
be INDISTINGUISHABLE from one big store, and the query-server fleet must
stay warm through rolls and replica loss.

Differentials run the same randomized event stream through a 3-shard
fleet (each shard a live in-process event server over one of the four
event backends) and a single reference store, then compare find /
aggregate / find_since exactly. Chaos scenarios kill a shard mid-flight:
reads inside a serving degraded scope answer partially and say so
(``shard_down``), reads outside fail loud, the composed fleet cursor
holds the dead shard's position so recovery delivers — delayed, never
lost."""

import datetime as dt
import json
import http.client
import random
import threading

import pytest

from predictionio_tpu.data import storage as storage_mod
from predictionio_tpu.data.api.event_server import (
    EventServer,
    EventServerConfig,
)
from predictionio_tpu.data.event import Event, new_event_id
from predictionio_tpu.data.storage.base import StorageError
from predictionio_tpu.fleet.ring import HashRing, stable_hash
from predictionio_tpu.fleet.router import CURSOR_KEY, FleetLEvents
from predictionio_tpu.utils import faults, metrics, resilience

pytestmark = pytest.mark.fleet

UTC = dt.timezone.utc
APP = 1
KEY = "fleet-secret"


@pytest.fixture(autouse=True)
def _clean_resilience():
    resilience.reset_breakers()
    faults.clear()
    yield
    resilience.reset_breakers()
    faults.clear()


def t(i):
    return dt.datetime(2022, 3, 1, tzinfo=UTC) + dt.timedelta(seconds=int(i))


def rate(user, item, at, val=4.0):
    # ids pre-assigned so the fleet and the reference store ingest
    # IDENTICAL events (backends mint ids for id-less inserts)
    return Event(event="rate", entity_type="user", entity_id=user,
                 target_entity_type="item", target_entity_id=item,
                 properties={"rating": val}, event_time=t(at),
                 event_id=new_event_id())


def setp(etype, eid, at, **props):
    return Event(event="$set", entity_type=etype, entity_id=eid,
                 properties=props, event_time=t(at),
                 event_id=new_event_id())


def random_stream(seed, n=48, n_users=9, n_items=6):
    rng = random.Random(seed)
    events = []
    for i in range(n):
        roll = rng.random()
        when = rng.randrange(n * 2)  # out-of-order, colliding times
        if roll < 0.5:
            events.append(rate(f"u{rng.randrange(n_users)}",
                               f"i{rng.randrange(n_items)}", when,
                               val=float(rng.randint(1, 5))))
        elif roll < 0.8:
            events.append(setp(rng.choice(("user", "item")),
                               f"e{rng.randrange(n_users)}", when,
                               **{rng.choice("abc"): i}))
        else:
            events.append(Event(
                event="$unset", entity_type="user",
                entity_id=f"e{rng.randrange(n_users)}",
                properties={rng.choice("abc"): 0}, event_time=t(when),
                event_id=new_event_id()))
    return events


def _shard_source(backend, tmp_path, idx, cleanup):
    if backend == "memory":
        return {"type": "memory"}
    if backend == "sqlite":
        return {"type": "sqlite", "path": str(tmp_path / f"shard{idx}.db")}
    if backend == "jsonlfs":
        return {"type": "jsonlfs", "path": str(tmp_path / f"shard{idx}"),
                "part_max_events": 7}
    # resthttp shard: the shard's OWN store is another event server —
    # the router must compose through a double wire hop unchanged
    inner = EventServer(
        EventServerConfig(ip="127.0.0.1", port=0, service_key="inner"),
        reg=storage_mod.StorageRegistry(storage_mod.StorageConfig(
            sources={"EV": {"type": "memory"},
                     "META": {"type": "memory"}},
            repositories={"EVENTDATA": "EV", "METADATA": "META",
                          "MODELDATA": "META"}))).start()
    cleanup.append(inner.stop)
    host, port = inner.address
    return {"type": "resthttp", "url": f"http://{host}:{port}",
            "service_key": "inner"}


class ShardCluster:
    """N live in-process event servers + the fleet DAO over them."""

    def __init__(self, backend, tmp_path, n=3):
        self.backend = backend
        self.tmp_path = tmp_path
        self.cleanup = []
        self.servers = []
        self.urls = []
        for i in range(n):
            self._start_shard(i)
        self.fleet = FleetLEvents({"urls": ",".join(self.urls),
                                   "service_key": KEY})

    def _registry(self, idx):
        return storage_mod.StorageRegistry(storage_mod.StorageConfig(
            sources={"EV": _shard_source(self.backend, self.tmp_path,
                                         idx, self.cleanup),
                     "META": {"type": "memory"}},
            repositories={"EVENTDATA": "EV", "METADATA": "META",
                          "MODELDATA": "META"}))

    def _start_shard(self, idx, port=0):
        srv = EventServer(
            EventServerConfig(ip="127.0.0.1", port=port, service_key=KEY),
            reg=self._registry(idx)).start()
        host, p = srv.address
        if idx < len(self.servers):
            self.servers[idx] = srv
        else:
            self.servers.append(srv)
            self.urls.append(f"http://{host}:{p}")
        return srv

    def kill_shard(self, idx):
        # stop() severs established keep-alive connections
        # (SeveringThreadingHTTPServer), so the router's pooled wires
        # die with the host — exactly like a real crash; the next use
        # takes the stale-redial path and gets connection-refused
        self.servers[idx].stop()

    def restart_shard(self, idx):
        """Rebind the SAME port with a fresh registry over the same
        backing path — the disk-backed backends come back with their
        data, like a restarted host."""
        port = int(self.urls[idx].rsplit(":", 1)[1])
        return self._start_shard(idx, port=port)

    def close(self):
        try:
            self.fleet.close()
        except Exception:
            pass
        for srv in self.servers:
            try:
                srv.stop()
            except Exception:
                pass
        for fn in self.cleanup:
            try:
                fn()
            except Exception:
                pass
        if self.backend == "sqlite":
            from predictionio_tpu.data.storage.sqlite import SqliteClient
            SqliteClient.shutdown_all()


@pytest.fixture(params=["memory", "sqlite", "jsonlfs", "resthttp"])
def cluster(request, tmp_path):
    c = ShardCluster(request.param, tmp_path)
    yield c
    c.close()


@pytest.fixture
def mem_cluster(tmp_path):
    c = ShardCluster("memory", tmp_path)
    yield c
    c.close()


@pytest.fixture
def reference():
    from predictionio_tpu.data.storage.memory import MemLEvents
    ref = MemLEvents({})
    ref.init(APP)
    return ref


def drain(le, cursor=None, limit=None, rounds=50):
    """find_since until dry; returns (events, final_cursor)."""
    out = []
    for _ in range(rounds):
        got, cursor = le.find_since(APP, cursor=cursor, limit=limit)
        if not got:
            break
        out.extend(got)
    return out, cursor


class TestHashRing:
    def test_stable_across_instances(self):
        a, b = HashRing(4), HashRing(4)
        keys = [f"user/u{i}" for i in range(200)]
        assert [a.node_for(k) for k in keys] == \
               [b.node_for(k) for k in keys]
        assert stable_hash("user/u1") == stable_hash("user/u1")

    def test_every_node_owns_keyspace(self):
        ring = HashRing(4)
        owners = {ring.node_for(f"user/u{i}") for i in range(500)}
        assert owners == {0, 1, 2, 3}

    def test_preference_is_a_permutation_led_by_owner(self):
        ring = HashRing(5)
        for k in ("user/u1", "item/i9", "x"):
            pref = list(ring.preference(k))
            assert pref[0] == ring.node_for(k)
            assert sorted(pref) == [0, 1, 2, 3, 4]


class TestFleetDifferential:
    """Router over N live shards == one big store, per backend."""

    def seed(self, cluster, reference, seed=0):
        cluster.fleet.init(APP)
        events = random_stream(seed)
        ids = cluster.fleet.insert_batch(events, APP)
        ref_ids = reference.insert_batch(events, APP)
        assert ids == ref_ids  # batch ids reassemble in input order
        return events, ids

    def test_find_matches_single_store(self, cluster, reference):
        self.seed(cluster, reference)
        fleet = cluster.fleet
        got = list(fleet.find(APP))
        want = list(reference.find(APP))
        assert {e.event_id for e in got} == {e.event_id for e in want}
        times = [e.event_time for e in got]
        assert times == sorted(times)  # global merge is time-ordered
        # filtered scans agree as sets (tie order is backend-private)
        for kw in ({"event_names": ("rate",)},
                   {"start_time": t(10), "until_time": t(60)},
                   {"entity_type": "user"}):
            assert {e.event_id for e in fleet.find(APP, **kw)} == \
                   {e.event_id for e in reference.find(APP, **kw)}, kw

    def test_entity_fast_path_exact(self, cluster, reference):
        events, _ = self.seed(cluster, reference)
        fleet = cluster.fleet
        entities = {(e.entity_type, e.entity_id) for e in events}
        for etype, eid in sorted(entities):
            for rev in (False, True):
                got = [e.event_id for e in fleet.find(
                    APP, entity_type=etype, entity_id=eid, reversed=rev)]
                want = [e.event_id for e in reference.find(
                    APP, entity_type=etype, entity_id=eid, reversed=rev)]
                assert got == want, (etype, eid, rev)

    def test_limit_cuts_global_order(self, cluster, reference):
        # distinct times: the first-k-by-time answer is unambiguous
        cluster.fleet.init(APP)
        events = [rate(f"u{i % 5}", f"i{i % 3}", at=i) for i in range(20)]
        cluster.fleet.insert_batch(events, APP)
        reference.insert_batch(events, APP)
        got = [e.event_id for e in cluster.fleet.find(APP, limit=7)]
        want = [e.event_id for e in reference.find(APP, limit=7)]
        assert got == want

    def test_aggregate_matches_single_store(self, cluster, reference):
        self.seed(cluster, reference, seed=3)
        for etype in ("user", "item"):
            got = cluster.fleet.aggregate_properties(APP, etype)
            want = reference.aggregate_properties(APP, etype)
            assert got == want, etype
            # and the replay reference over the merged fleet scan agrees
            assert cluster.fleet.aggregate_properties_replay(
                APP, etype) == want, etype

    def test_find_since_drains_exactly_once(self, cluster, reference):
        events, ids = self.seed(cluster, reference, seed=5)
        got, cursor = drain(cluster.fleet, limit=7)
        assert sorted(e.event_id for e in got) == sorted(ids)
        assert len(got) == len(ids)  # exactly once, no duplicates
        # incremental: only the new arrivals, in one fleet cursor
        fresh = [rate("u-new", "i1", at=500 + i) for i in range(5)]
        fresh_ids = cluster.fleet.insert_batch(fresh, APP)
        got2, cursor = drain(cluster.fleet, cursor=cursor)
        assert sorted(e.event_id for e in got2) == sorted(fresh_ids)
        assert cluster.fleet.find_since(APP, cursor=cursor)[0] == []


class TestFleetCursor:
    """The composed cursor fold-in tails: anchor, drain, watermark."""

    def test_tail_cursor_skips_history(self, mem_cluster):
        fleet = mem_cluster.fleet
        fleet.init(APP)
        fleet.insert_batch([rate(f"u{i}", "i0", at=i)
                            for i in range(12)], APP)
        cur = fleet.tail_cursor(APP)
        assert set(cur[CURSOR_KEY]) == set(mem_cluster.urls)
        fresh_ids = fleet.insert_batch(
            [rate(f"u{i}", "i1", at=100 + i) for i in range(9)], APP)
        got, cur2 = drain(fleet, cursor=cur, limit=4)
        assert sorted(e.event_id for e in got) == sorted(fresh_ids)
        assert fleet.find_since(APP, cursor=cur2)[0] == []

    def test_watermark_composes(self, mem_cluster):
        fleet = mem_cluster.fleet
        fleet.init(APP)
        ids = fleet.insert_batch([rate(f"u{i}", "i0", at=i)
                                  for i in range(6)], APP)
        wm = fleet.tail_watermark(APP)
        assert wm is not None
        assert set(wm["cursor"][CURSOR_KEY]) == set(mem_cluster.urls)
        # the composed watermark is the LATEST shard's last event
        assert wm["lastEventId"] == ids[-1]

    def test_shard_metrics_labeled(self, mem_cluster):
        fleet = mem_cluster.fleet
        fleet.init(APP)
        fleet.insert_batch([rate(f"u{i}", "i0", at=i)
                            for i in range(12)], APP)
        list(fleet.find(APP))
        per_shard = [
            metrics.STORAGE_OP_LATENCY.child(
                backend="fleet", op="find",
                shard=str(i)).summary()["count"]
            for i in range(len(mem_cluster.urls))]
        assert all(c > 0 for c in per_shard)


@pytest.mark.chaos
class TestDeadShard:
    def _entity_on(self, fleet, shard):
        for i in range(1000):
            if fleet._shard_for_entity("user", f"u{i}") == shard:
                return f"u{i}"
        raise AssertionError("ring left a shard empty")

    def seed(self, cluster, n=30):
        cluster.fleet.init(APP)
        return cluster.fleet.insert_batch(
            [rate(f"u{i % 10}", f"i{i % 4}", at=i) for i in range(n)], APP)

    def test_scatter_read_degrades_inside_scope_only(self, mem_cluster):
        fleet = mem_cluster.fleet
        self.seed(mem_cluster)
        before = {e.event_id for e in fleet.find(APP)}
        mem_cluster.kill_shard(1)
        # training/admin path: a lost shard is a loud failure
        with pytest.raises(StorageError):
            list(fleet.find(APP))
        # serving path: partial answer, marked
        with resilience.degraded_scope() as marks:
            got = {e.event_id for e in fleet.find(APP)}
        assert "shard_down" in marks
        assert got and got < before
        with resilience.degraded_scope() as marks:
            agg = fleet.aggregate_properties(APP, "user")
        assert {"shard_down", "partial_aggregation"} <= set(marks)
        assert isinstance(agg, dict)
        assert fleet.topology()["partialReads"] >= 2

    def test_entity_fast_path_degrades_to_empty(self, mem_cluster):
        fleet = mem_cluster.fleet
        self.seed(mem_cluster)
        dead_user = self._entity_on(fleet, 1)
        live_user = self._entity_on(fleet, 0)
        fleet.insert(rate(dead_user, "i9", at=900), APP)
        fleet.insert(rate(live_user, "i9", at=901), APP)
        mem_cluster.kill_shard(1)
        with resilience.degraded_scope() as marks:
            dead_read = list(fleet.find(APP, entity_type="user",
                                        entity_id=dead_user))
            live_read = list(fleet.find(APP, entity_type="user",
                                        entity_id=live_user))
        assert dead_read == [] and "shard_down" in marks
        assert any(e.target_entity_id == "i9" for e in live_read)

    def test_writes_fail_loud(self, mem_cluster):
        fleet = mem_cluster.fleet
        fleet.init(APP)
        dead_user = self._entity_on(fleet, 2)
        live_user = self._entity_on(fleet, 0)
        mem_cluster.kill_shard(2)
        assert fleet.insert(rate(live_user, "i1", at=1), APP)
        with pytest.raises(StorageError):
            fleet.insert(rate(dead_user, "i1", at=2), APP)
        with pytest.raises(StorageError):
            fleet.insert_batch([rate(live_user, "i2", at=3),
                                rate(dead_user, "i2", at=4)], APP)

    def test_cursor_survives_shard_restart(self, tmp_path):
        """The fold-in guarantee: a dead shard's events are DELAYED,
        never LOST — its cursor entry freezes while it's down and the
        tail resumes from exactly there after restart."""
        c = ShardCluster("jsonlfs", tmp_path)  # disk-backed: survives
        try:
            fleet = c.fleet
            fleet.init(APP)
            fleet.insert_batch([rate(f"u{i}", "i0", at=i)
                                for i in range(12)], APP)
            _, cursor = drain(fleet)
            pre_death = fleet.insert_batch(
                [rate(f"u{i}", "i1", at=50 + i) for i in range(9)], APP)
            c.kill_shard(1)
            with resilience.degraded_scope() as marks:
                got, cursor = drain(fleet, cursor=cursor)
            assert "shard_down" in marks
            survivors = {e.event_id for e in got}
            missing = set(pre_death) - survivors
            assert missing  # the dead shard really held some of them
            c.restart_shard(1)
            resilience.reset_breakers()  # operator analog of cooldown
            got2, cursor = drain(fleet, cursor=cursor)
            assert {e.event_id for e in got2} == missing
        finally:
            c.close()

    def test_all_shards_down_raises_even_degraded(self, mem_cluster):
        fleet = mem_cluster.fleet
        self.seed(mem_cluster)
        for i in range(len(mem_cluster.urls)):
            mem_cluster.kill_shard(i)
        with resilience.degraded_scope():
            with pytest.raises(StorageError):
                list(fleet.find(APP))
            with pytest.raises(StorageError):
                fleet.find_since(APP)

    def test_transient_faults_absorbed_by_wire(self, mem_cluster):
        """Injected connect-refusals ride the per-shard wire's retry
        policy — the fleet answer stays complete and unmarked."""
        fleet = mem_cluster.fleet
        ids = self.seed(mem_cluster)
        faults.install("backend=resthttp,kind=refuse,every=3,seed=7")
        with resilience.degraded_scope() as marks:
            got = {e.event_id for e in fleet.find(APP)}
        assert got == set(ids)
        assert "shard_down" not in marks


class TestQueryFleet:
    @pytest.fixture
    def fleet(self, mem_storage):
        from test_query_server import seed_ratings, train_once
        from predictionio_tpu.fleet.balancer import QueryFleet
        from predictionio_tpu.workflow import ServerConfig

        seed_ratings()
        train_once()
        qf = QueryFleet(ServerConfig(ip="127.0.0.1", port=0),
                        replicas=3).start(undeploy_stale=False)
        yield qf
        qf.stop()

    def _post(self, addr, path, body, headers=None):
        host, port = addr
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("POST", path, body=json.dumps(body).encode(),
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        return resp.status, json.loads(data) if data else None

    def _get(self, addr, path):
        host, port = addr
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", path)
        resp = conn.getresponse()
        data = json.loads(resp.read())
        conn.close()
        return resp.status, data

    def test_routing_is_user_sticky(self, fleet):
        addr = fleet.address
        for _ in range(4):
            status, payload = self._post(addr, "/queries.json",
                                         {"user": "u1", "num": 2})
            assert status == 200 and payload["itemScores"]
        counts = [r.server.status()["requestCount"]
                  for r in fleet.replicas]
        # one replica owns u1; the others never saw a query
        assert sorted(counts) == [0, 0, 4]
        owner = counts.index(4)
        assert owner == fleet.ring.node_for("u1")

    def test_health_stats_and_topology(self, fleet):
        status, health = self._get(fleet.address, "/healthz")
        assert status == 200 and health["ready"] is True
        status, stats = self._get(fleet.address, "/stats.json")
        assert status == 200
        topo = stats["fleet"]
        assert topo["type"] == "queryFleet"
        assert topo["readyReplicas"] == 3
        assert len(topo["replicas"]) == 3

    def test_rolling_reload_stays_warm(self, fleet):
        addr = fleet.address
        stop = threading.Event()
        failures = []

        def hammer():
            while not stop.is_set():
                try:
                    status, payload = self._post(
                        addr, "/queries.json", {"user": "u3", "num": 2})
                    if status != 200:
                        failures.append(status)
                except Exception as e:  # pragma: no cover - fail below
                    failures.append(repr(e))

        thread = threading.Thread(target=hammer, daemon=True)
        thread.start()
        try:
            status, info = self._post(addr, "/reload", {})
        finally:
            stop.set()
            thread.join(timeout=10)
        assert status == 200
        assert len(info["replicas"]) == 3  # every replica swapped
        assert not failures  # the fleet was never cold

    def test_balancer_bind_failure_leaves_no_replicas_running(
            self, mem_storage):
        import socket as socket_mod

        from test_query_server import seed_ratings, train_once
        from predictionio_tpu.fleet.balancer import QueryFleet
        from predictionio_tpu.workflow import ServerConfig

        seed_ratings()
        train_once()
        blocker = socket_mod.socket()
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        port = blocker.getsockname()[1]
        qf = QueryFleet(ServerConfig(ip="127.0.0.1", port=port),
                        replicas=2)
        try:
            with pytest.raises(OSError):
                qf.start(undeploy_stale=False)
            assert all(rep.server._httpd is None for rep in qf.replicas), \
                "an EADDRINUSE balancer bind must not leak replicas"
        finally:
            blocker.close()

    def test_downgrade_abort_reports_roll_progress(self, fleet):
        """A mid-roll downgrade refusal must tell the operator how far
        the roll got: the 409 body lists the already-swapped replicas."""
        from predictionio_tpu.workflow import ReloadDowngradeError

        rep1 = fleet.replicas[1]
        orig = rep1.server.reload

        def refuse():
            raise ReloadDowngradeError("refusing to reload: downgrade")

        rep1.server.reload = refuse
        try:
            status, payload = self._post(fleet.address, "/reload", {})
        finally:
            rep1.server.reload = orig
        assert status == 409
        assert "refusing" in payload["message"]
        assert [r["replica"] for r in payload["replicas"]] == [0]
        # the fleet stayed warm: nothing was stopped, no replica drains
        assert all(not rep.draining for rep in fleet.replicas)
        s, health = self._get(fleet.address, "/healthz")
        assert s == 200 and health["ready"] is True

    def test_replica_down_fails_over(self, fleet):
        addr = fleet.address
        owner = fleet.ring.node_for("u5")
        fleet.replicas[owner].server.stop()
        status, payload = self._post(addr, "/queries.json",
                                     {"user": "u5", "num": 2})
        assert status == 200 and payload["itemScores"]
        assert payload["degraded"] is True
        assert "replica_down" in payload["degradedReasons"]
        # and the fleet still reports ready (one replica is enough)
        status, health = self._get(addr, "/healthz")
        assert status == 200 and health["ready"] is True


class TestWatermarkCompare:
    def test_fleet_watermark_compares_instants_not_strings(self):
        from predictionio_tpu.fleet.router import _time_newer

        # 11:00-02:00 IS 13:00Z — later than 12:00Z, though the string
        # "11..." sorts before "12..."; shards may render offsets
        # differently and the fleet watermark must not care
        assert _time_newer("2022-03-01T11:00:00-02:00",
                           "2022-03-01T12:00:00Z")
        # same instant under two offsets: neither is strictly newer
        assert not _time_newer("2022-03-01T12:00:00+00:00",
                               "2022-03-01T13:00:00+01:00")
        assert not _time_newer("2022-03-01T13:00:00+01:00",
                               "2022-03-01T12:00:00+00:00")
        # naive timestamps are read as UTC; datetimes pass through
        assert _time_newer(dt.datetime(2022, 3, 1, 12, 0, 1),
                           "2022-03-01T12:00:00Z")
        # unparseable values fall back to string order
        assert _time_newer("b", "a")


class TestWireConnectionReuse:
    def test_keep_alive_pool_reuses_connections(self, mem_storage):
        from predictionio_tpu.data.storage.resthttp import RestLEvents

        server = EventServer(
            EventServerConfig(ip="127.0.0.1", port=0, service_key=KEY),
            reg=mem_storage).start()
        host, port = server.address
        le = RestLEvents({"url": f"http://{host}:{port}",
                          "service_key": KEY})
        try:
            le.init(APP)
            le.insert_batch([rate(f"u{i}", "i0", at=i)
                             for i in range(5)], APP)
            for _ in range(4):
                assert len(list(le.find(APP))) == 5
            assert le._w.pool_reuses >= 3
        finally:
            le.close()
            server.stop()
