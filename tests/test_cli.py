"""CLI tests: pio status / app verbs (Console.scala parity, growing)."""

import pytest

from predictionio_tpu.data import storage
from predictionio_tpu.tools.cli import main


class TestCli:
    def test_version(self, capsys):
        assert main(["version"]) == 0
        from predictionio_tpu import __version__
        assert capsys.readouterr().out.strip() == __version__

    def test_status(self, mem_storage, capsys):
        assert main(["status"]) == 0
        out = capsys.readouterr().out
        assert "ready to go" in out

    def test_app_lifecycle(self, mem_storage, capsys):
        assert main(["app", "new", "myapp", "--description", "d"]) == 0
        out = capsys.readouterr().out
        assert "Access Key:" in out
        app = storage.get_metadata_apps().get_by_name("myapp")
        assert app is not None
        keys = storage.get_metadata_access_keys().get_by_appid(app.id)
        assert len(keys) == 1

        assert main(["app", "new", "myapp"]) == 1  # duplicate

        assert main(["app", "list"]) == 0
        assert "myapp" in capsys.readouterr().out

        assert main(["app", "show", "myapp"]) == 0
        assert main(["app", "show", "nope"]) == 1
        capsys.readouterr()

        # data-delete wipes events but keeps the app
        from predictionio_tpu.data.event import Event
        le = storage.get_levents()
        le.insert(Event(event="rate", entity_type="user", entity_id="u",
                        target_entity_type="item", target_entity_id="i"),
                  app.id)
        assert main(["app", "data-delete", "myapp", "-f"]) == 0
        assert list(le.find(app.id)) == []
        assert storage.get_metadata_apps().get_by_name("myapp") is not None

        assert main(["app", "delete", "myapp", "-f"]) == 0
        assert storage.get_metadata_apps().get_by_name("myapp") is None
        assert storage.get_metadata_access_keys().get_by_appid(app.id) == []
