"""CLI tests: pio status / app verbs (Console.scala parity, growing)."""

import pytest

from predictionio_tpu.data import storage
from predictionio_tpu.tools.cli import main


class TestCli:
    def test_version(self, capsys):
        assert main(["version"]) == 0
        from predictionio_tpu import __version__
        assert capsys.readouterr().out.strip() == __version__

    def test_status(self, mem_storage, capsys):
        assert main(["status"]) == 0
        out = capsys.readouterr().out
        assert "ready to go" in out

    def test_app_lifecycle(self, mem_storage, capsys):
        assert main(["app", "new", "myapp", "--description", "d"]) == 0
        out = capsys.readouterr().out
        assert "Access Key:" in out
        app = storage.get_metadata_apps().get_by_name("myapp")
        assert app is not None
        keys = storage.get_metadata_access_keys().get_by_appid(app.id)
        assert len(keys) == 1

        assert main(["app", "new", "myapp"]) == 1  # duplicate

        assert main(["app", "list"]) == 0
        assert "myapp" in capsys.readouterr().out

        assert main(["app", "show", "myapp"]) == 0
        assert main(["app", "show", "nope"]) == 1
        capsys.readouterr()

        # data-delete wipes events but keeps the app
        from predictionio_tpu.data.event import Event
        le = storage.get_levents()
        le.insert(Event(event="rate", entity_type="user", entity_id="u",
                        target_entity_type="item", target_entity_id="i"),
                  app.id)
        assert main(["app", "data-delete", "myapp", "-f"]) == 0
        assert list(le.find(app.id)) == []
        assert storage.get_metadata_apps().get_by_name("myapp") is not None

        assert main(["app", "delete", "myapp", "-f"]) == 0
        assert storage.get_metadata_apps().get_by_name("myapp") is None
        assert storage.get_metadata_access_keys().get_by_appid(app.id) == []

    def test_app_data_cleanup_and_trim(self, mem_storage, capsys):
        """data-cleanup deletes pre-cutoff events (cleanup-app parity);
        data-trim copies a time window to another app (trim-app parity)."""
        import datetime as dt

        from predictionio_tpu.data.event import Event

        UTC = dt.timezone.utc
        main(["app", "new", "srcapp"])
        main(["app", "new", "dstapp"])
        src = storage.get_metadata_apps().get_by_name("srcapp")
        dst = storage.get_metadata_apps().get_by_name("dstapp")
        le = storage.get_levents()
        for i in range(6):
            le.insert(Event(event="rate", entity_type="user",
                            entity_id=f"u{i}", target_entity_type="item",
                            target_entity_id="i1",
                            event_time=dt.datetime(2022, 1, 1 + i,
                                                   tzinfo=UTC)), src.id)
        capsys.readouterr()

        # trim the middle window into dstapp first
        assert main(["app", "data-trim", "srcapp", "--dst", "dstapp",
                     "--start", "2022-01-02T00:00:00+00:00",
                     "--until", "2022-01-05T00:00:00+00:00"]) == 0
        assert "Copied 3 events" in capsys.readouterr().out
        copied = list(le.find(dst.id))
        assert len(copied) == 3
        assert {e.entity_id for e in copied} == {"u1", "u2", "u3"}

        # idempotent: a retry copies nothing new (ids already present)
        assert main(["app", "data-trim", "srcapp", "--dst", "dstapp",
                     "--start", "2022-01-02T00:00:00+00:00",
                     "--until", "2022-01-05T00:00:00+00:00"]) == 0
        assert "Copied 0 events" in capsys.readouterr().out
        assert len(list(le.find(dst.id))) == 3

        # cleanup everything before Jan 4 in the source
        assert main(["app", "data-cleanup", "srcapp", "-f",
                     "--before", "2022-01-04T00:00:00+00:00"]) == 0
        out = capsys.readouterr().out
        assert "Removed 3 events" in out
        rest = list(le.find(src.id))
        assert {e.entity_id for e in rest} == {"u3", "u4", "u5"}
        # destination untouched by the source cleanup
        assert len(list(le.find(dst.id))) == 3

        # error paths
        assert main(["app", "data-cleanup", "nope", "-f",
                     "--before", "2022-01-01T00:00:00+00:00"]) == 1
        assert main(["app", "data-cleanup", "srcapp", "-f",
                     "--before", "garbage"]) == 1
        assert main(["app", "data-trim", "srcapp", "--dst", "nope"]) == 1

    def test_channel_lifecycle(self, mem_storage, capsys):
        main(["app", "new", "chanapp"])
        assert main(["app", "channel-new", "chanapp", "weblogs"]) == 0
        assert main(["app", "channel-new", "chanapp", "weblogs"]) == 1  # dup
        assert main(["app", "channel-new", "chanapp", "bad name!"]) == 1
        assert main(["app", "channel-new", "noapp", "c"]) == 1
        capsys.readouterr()
        assert main(["app", "show", "chanapp"]) == 0
        assert "weblogs" in capsys.readouterr().out
        assert main(["app", "channel-delete", "chanapp", "weblogs",
                     "-f"]) == 0
        app = storage.get_metadata_apps().get_by_name("chanapp")
        assert storage.get_metadata_channels().get_by_appid(app.id) == []

    def test_accesskey_lifecycle(self, mem_storage, capsys):
        main(["app", "new", "akapp"])
        capsys.readouterr()
        assert main(["accesskey", "new", "akapp", "--events", "rate",
                     "buy"]) == 0
        out = capsys.readouterr().out
        key = out.split("access key:")[-1].strip()
        assert len(key) == 64
        app = storage.get_metadata_apps().get_by_name("akapp")
        keys = storage.get_metadata_access_keys().get_by_appid(app.id)
        assert any(k.events == ("rate", "buy") for k in keys)

        assert main(["accesskey", "list", "akapp"]) == 0
        assert key in capsys.readouterr().out
        assert main(["accesskey", "delete", key]) == 0
        assert main(["accesskey", "delete", key]) == 1
        assert main(["accesskey", "new", "noapp"]) == 1


class TestExportImport:
    def test_roundtrip(self, mem_storage, tmp_path, capsys):
        from predictionio_tpu.data.event import Event

        main(["app", "new", "expapp"])
        app = storage.get_metadata_apps().get_by_name("expapp")
        le = storage.get_levents()
        for i in range(5):
            le.insert(Event(event="rate", entity_type="user",
                            entity_id=f"u{i}", target_entity_type="item",
                            target_entity_id="i1",
                            properties={"rating": float(i)}), app.id)
        out = str(tmp_path / "events.jsonl")
        assert main(["export", "--app-name", "expapp", "--output", out]) == 0
        assert len(open(out).read().strip().splitlines()) == 5

        main(["app", "new", "impapp"])
        assert main(["import", "--app-name", "impapp", "--input", out]) == 0
        app2 = storage.get_metadata_apps().get_by_name("impapp")
        events = list(le.find(app2.id))
        assert len(events) == 5
        assert {e.entity_id for e in events} == {f"u{i}" for i in range(5)}

    def test_bad_args(self, mem_storage, tmp_path, capsys):
        assert main(["export", "--app-name", "ghost", "--output",
                     str(tmp_path / "x")]) == 1
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"event": "", "entityType": "u", "entityId": "1"}\n')
        main(["app", "new", "impbad"])
        assert main(["import", "--app-name", "impbad", "--input",
                     str(bad)]) == 1


class TestTemplateAndLifecycleVerbs:
    def seed(self, app_name="cliapp", n_users=12):
        import datetime as dt
        import numpy as np
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage.base import App

        aid = storage.get_metadata_apps().insert(App(0, app_name))
        le = storage.get_levents()
        le.init(aid)
        rng = np.random.default_rng(1)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        le.insert_batch([
            Event(event="rate", entity_type="user", entity_id=f"u{u}",
                  target_entity_type="item",
                  target_entity_id=f"i{rng.integers(0, 6)}",
                  properties={"rating": float(rng.integers(1, 6))},
                  event_time=t0)
            for u in range(n_users) for _ in range(5)], aid)
        return aid

    def test_template_list_get_build_train(self, mem_storage, tmp_path,
                                           capsys, monkeypatch):
        import json

        assert main(["template", "list"]) == 0
        assert "recommendation" in capsys.readouterr().out

        engine_dir = tmp_path / "myengine"
        assert main(["template", "get", "recommendation",
                     str(engine_dir)]) == 0
        variant_path = engine_dir / "engine.json"
        assert main(["template", "get", "recommendation",
                     str(engine_dir)]) == 1  # already exists
        assert main(["template", "get", "nope", str(tmp_path / "x")]) == 1
        capsys.readouterr()

        self.seed()
        variant = json.loads(variant_path.read_text())
        variant["datasource"]["params"]["appName"] = "cliapp"
        variant["algorithms"][0]["params"].update(
            {"rank": 4, "numIterations": 2})
        variant_path.write_text(json.dumps(variant))

        assert main(["build", "--engine-variant", str(variant_path)]) == 0
        assert "ready for training" in capsys.readouterr().out

        assert main(["train", "--engine-variant", str(variant_path)]) == 0
        out = capsys.readouterr().out
        assert "Training completed" in out
        iid = out.split("ID:")[-1].strip()
        instance = storage.get_metadata_engine_instances().get(iid)
        assert instance.status == "COMPLETED"
        assert storage.get_model_data_models().get(iid) is not None

    def test_train_stop_after_read(self, mem_storage, tmp_path, capsys):
        import json

        engine_dir = tmp_path / "e2"
        main(["template", "get", "recommendation", str(engine_dir)])
        self.seed("stopapp")
        variant_path = engine_dir / "engine.json"
        variant = json.loads(variant_path.read_text())
        variant["datasource"]["params"]["appName"] = "stopapp"
        variant_path.write_text(json.dumps(variant))
        capsys.readouterr()
        assert main(["train", "--engine-variant", str(variant_path),
                     "--stop-after-read"]) == 0
        assert "interrupted" in capsys.readouterr().out

    def test_build_errors(self, mem_storage, tmp_path, capsys):
        import json

        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"engineFactory": "nope.nope:f"}))
        assert main(["build", "--engine-variant", str(bad)]) == 1
        none = tmp_path / "none.json"
        none.write_text(json.dumps({}))
        assert main(["build", "--engine-variant", str(none)]) == 1

    def test_eval_verb(self, mem_storage, capsys):
        self.seed("evalapp", n_users=10)
        assert main(["eval", "tests.cli_eval_fixture:make_evaluation",
                     "tests.cli_eval_fixture:make_generator"]) == 0
        out = capsys.readouterr().out
        assert "[INFO]" in out
        rows = storage.get_metadata_evaluation_instances().get_completed()
        assert len(rows) == 1
        assert rows[0].evaluation_class == (
            "tests.cli_eval_fixture:make_evaluation")
