"""CLI tests: pio status / app verbs (Console.scala parity, growing)."""

import pytest

from predictionio_tpu.data import storage
from predictionio_tpu.tools.cli import main


class TestCli:
    def test_version(self, capsys):
        assert main(["version"]) == 0
        from predictionio_tpu import __version__
        assert capsys.readouterr().out.strip() == __version__

    def test_status(self, mem_storage, capsys):
        assert main(["status"]) == 0
        out = capsys.readouterr().out
        assert "ready to go" in out

    def test_app_lifecycle(self, mem_storage, capsys):
        assert main(["app", "new", "myapp", "--description", "d"]) == 0
        out = capsys.readouterr().out
        assert "Access Key:" in out
        app = storage.get_metadata_apps().get_by_name("myapp")
        assert app is not None
        keys = storage.get_metadata_access_keys().get_by_appid(app.id)
        assert len(keys) == 1

        assert main(["app", "new", "myapp"]) == 1  # duplicate

        assert main(["app", "list"]) == 0
        assert "myapp" in capsys.readouterr().out

        assert main(["app", "show", "myapp"]) == 0
        assert main(["app", "show", "nope"]) == 1
        capsys.readouterr()

        # data-delete wipes events but keeps the app
        from predictionio_tpu.data.event import Event
        le = storage.get_levents()
        le.insert(Event(event="rate", entity_type="user", entity_id="u",
                        target_entity_type="item", target_entity_id="i"),
                  app.id)
        assert main(["app", "data-delete", "myapp", "-f"]) == 0
        assert list(le.find(app.id)) == []
        assert storage.get_metadata_apps().get_by_name("myapp") is not None

        assert main(["app", "delete", "myapp", "-f"]) == 0
        assert storage.get_metadata_apps().get_by_name("myapp") is None
        assert storage.get_metadata_access_keys().get_by_appid(app.id) == []

    def test_app_data_cleanup_and_trim(self, mem_storage, capsys):
        """data-cleanup deletes pre-cutoff events (cleanup-app parity);
        data-trim copies a time window to another app (trim-app parity)."""
        import datetime as dt

        from predictionio_tpu.data.event import Event

        UTC = dt.timezone.utc
        main(["app", "new", "srcapp"])
        main(["app", "new", "dstapp"])
        src = storage.get_metadata_apps().get_by_name("srcapp")
        dst = storage.get_metadata_apps().get_by_name("dstapp")
        le = storage.get_levents()
        for i in range(6):
            le.insert(Event(event="rate", entity_type="user",
                            entity_id=f"u{i}", target_entity_type="item",
                            target_entity_id="i1",
                            event_time=dt.datetime(2022, 1, 1 + i,
                                                   tzinfo=UTC)), src.id)
        capsys.readouterr()

        # trim the middle window into dstapp first
        assert main(["app", "data-trim", "srcapp", "--dst", "dstapp",
                     "--start", "2022-01-02T00:00:00+00:00",
                     "--until", "2022-01-05T00:00:00+00:00"]) == 0
        assert "Copied 3 events" in capsys.readouterr().out
        copied = list(le.find(dst.id))
        assert len(copied) == 3
        assert {e.entity_id for e in copied} == {"u1", "u2", "u3"}

        # idempotent: a retry copies nothing new (ids already present)
        assert main(["app", "data-trim", "srcapp", "--dst", "dstapp",
                     "--start", "2022-01-02T00:00:00+00:00",
                     "--until", "2022-01-05T00:00:00+00:00"]) == 0
        assert "Copied 0 events" in capsys.readouterr().out
        assert len(list(le.find(dst.id))) == 3

        # cleanup everything before Jan 4 in the source
        assert main(["app", "data-cleanup", "srcapp", "-f",
                     "--before", "2022-01-04T00:00:00+00:00"]) == 0
        out = capsys.readouterr().out
        assert "Removed 3 events" in out
        rest = list(le.find(src.id))
        assert {e.entity_id for e in rest} == {"u3", "u4", "u5"}
        # destination untouched by the source cleanup
        assert len(list(le.find(dst.id))) == 3

        # error paths
        assert main(["app", "data-cleanup", "nope", "-f",
                     "--before", "2022-01-01T00:00:00+00:00"]) == 1
        assert main(["app", "data-cleanup", "srcapp", "-f",
                     "--before", "garbage"]) == 1
        assert main(["app", "data-trim", "srcapp", "--dst", "nope"]) == 1

    def test_channel_lifecycle(self, mem_storage, capsys):
        main(["app", "new", "chanapp"])
        assert main(["app", "channel-new", "chanapp", "weblogs"]) == 0
        assert main(["app", "channel-new", "chanapp", "weblogs"]) == 1  # dup
        assert main(["app", "channel-new", "chanapp", "bad name!"]) == 1
        assert main(["app", "channel-new", "noapp", "c"]) == 1
        capsys.readouterr()
        assert main(["app", "show", "chanapp"]) == 0
        assert "weblogs" in capsys.readouterr().out
        assert main(["app", "channel-delete", "chanapp", "weblogs",
                     "-f"]) == 0
        app = storage.get_metadata_apps().get_by_name("chanapp")
        assert storage.get_metadata_channels().get_by_appid(app.id) == []

    def test_accesskey_lifecycle(self, mem_storage, capsys):
        main(["app", "new", "akapp"])
        capsys.readouterr()
        assert main(["accesskey", "new", "akapp", "--events", "rate",
                     "buy"]) == 0
        out = capsys.readouterr().out
        key = out.split("access key:")[-1].strip()
        assert len(key) == 64
        app = storage.get_metadata_apps().get_by_name("akapp")
        keys = storage.get_metadata_access_keys().get_by_appid(app.id)
        assert any(k.events == ("rate", "buy") for k in keys)

        assert main(["accesskey", "list", "akapp"]) == 0
        assert key in capsys.readouterr().out
        assert main(["accesskey", "delete", key]) == 0
        assert main(["accesskey", "delete", key]) == 1
        assert main(["accesskey", "new", "noapp"]) == 1


class TestExportImport:
    def test_roundtrip(self, mem_storage, tmp_path, capsys):
        from predictionio_tpu.data.event import Event

        main(["app", "new", "expapp"])
        app = storage.get_metadata_apps().get_by_name("expapp")
        le = storage.get_levents()
        for i in range(5):
            le.insert(Event(event="rate", entity_type="user",
                            entity_id=f"u{i}", target_entity_type="item",
                            target_entity_id="i1",
                            properties={"rating": float(i)}), app.id)
        out = str(tmp_path / "events.jsonl")
        assert main(["export", "--app-name", "expapp", "--output", out]) == 0
        assert len(open(out).read().strip().splitlines()) == 5

        main(["app", "new", "impapp"])
        assert main(["import", "--app-name", "impapp", "--input", out]) == 0
        app2 = storage.get_metadata_apps().get_by_name("impapp")
        events = list(le.find(app2.id))
        assert len(events) == 5
        assert {e.entity_id for e in events} == {f"u{i}" for i in range(5)}

    def test_bad_args(self, mem_storage, tmp_path, capsys):
        assert main(["export", "--app-name", "ghost", "--output",
                     str(tmp_path / "x")]) == 1
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"event": "", "entityType": "u", "entityId": "1"}\n')
        main(["app", "new", "impbad"])
        assert main(["import", "--app-name", "impbad", "--input",
                     str(bad)]) == 1

    def test_columnar_roundtrip_full_fidelity(self, mem_storage, tmp_path,
                                              capsys):
        """The Parquet-analog format: every field survives a columnar
        round trip, including tags/prId/no-target events and None
        properties, and import auto-detects the format."""
        import datetime as dt

        from predictionio_tpu.data.event import Event

        main(["app", "new", "colapp"])
        app = storage.get_metadata_apps().get_by_name("colapp")
        le = storage.get_levents()
        t0 = dt.datetime(2021, 5, 1, tzinfo=dt.timezone.utc)
        evs = [
            Event(event="rate", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i1",
                  properties={"rating": 4.5, "note": "great"},
                  tags=("a", "b"), pr_id="pr9", event_time=t0),
            Event(event="$set", entity_type="user", entity_id="u2",
                  properties={"vip": True},
                  event_time=t0 + dt.timedelta(seconds=1)),
            Event(event="view", entity_type="user", entity_id="u3",
                  target_entity_type="item", target_entity_id="i2",
                  event_time=t0 + dt.timedelta(seconds=2)),
        ]
        ids = le.insert_batch(evs, app.id)
        out = str(tmp_path / "events.npz")
        assert main(["export", "--app-name", "colapp", "--output", out,
                     "--format", "columnar"]) == 0
        from predictionio_tpu.tools.export_import import is_columnar_export
        assert is_columnar_export(out)

        main(["app", "new", "colimp"])
        assert main(["import", "--app-name", "colimp", "--input",
                     out]) == 0
        app2 = storage.get_metadata_apps().get_by_name("colimp")
        got = {e.entity_id: e for e in le.find(app2.id)}
        assert set(got) == {"u1", "u2", "u3"}
        e1 = got["u1"]
        assert e1.event_id == ids[0]  # ids preserved
        assert e1.properties.fields == {"rating": 4.5, "note": "great"}
        assert e1.tags == ("a", "b") and e1.pr_id == "pr9"
        assert e1.event_time == t0
        assert got["u2"].target_entity_type is None
        assert got["u2"].properties.fields == {"vip": True}
        assert got["u3"].properties.fields == {}

    def test_columnar_null_sentinel_string_survives(self, mem_storage,
                                                    tmp_path, capsys):
        """Regression (advisor finding): the columnar codec used the
        in-band string ``"\\0N"`` as its null sentinel, so a GENUINE
        ``"\\0N"`` value (entity id, prId...) decoded back as None. The
        null mask is now out-of-band; any string value round-trips."""
        import datetime as dt

        from predictionio_tpu.data.event import Event
        from predictionio_tpu.tools import export_import as ei

        # the unit mechanics: sentinel-looking values encode losslessly
        vals = ["\0N", None, "a", "\0N", "", None]
        codes, labels = ei._dict_encode(vals)
        assert ei._dict_decode(codes, labels) == vals
        codes, labels = ei._dict_encode([None, None])
        assert ei._dict_decode(codes, labels) == [None, None]

        main(["app", "new", "sentapp"])
        app = storage.get_metadata_apps().get_by_name("sentapp")
        le = storage.get_levents()
        t0 = dt.datetime(2021, 5, 1, tzinfo=dt.timezone.utc)
        le.insert_batch([
            Event(event="rate", entity_type="user", entity_id="\0N",
                  target_entity_type="item", target_entity_id="i1",
                  pr_id="\0N", event_time=t0),
            Event(event="view", entity_type="user", entity_id="u2",
                  target_entity_type="item", target_entity_id="i2",
                  event_time=t0),
        ], app.id)
        out = str(tmp_path / "events.npz")
        assert main(["export", "--app-name", "sentapp", "--output", out,
                     "--format", "columnar"]) == 0
        main(["app", "new", "sentimp"])
        assert main(["import", "--app-name", "sentimp", "--input",
                     out]) == 0
        app2 = storage.get_metadata_apps().get_by_name("sentimp")
        got = {e.entity_id: e for e in le.find(app2.id)}
        assert set(got) == {"\0N", "u2"}
        assert got["\0N"].pr_id == "\0N"
        assert got["u2"].pr_id is None

    def test_columnar_roundtrip_sqlite_raw_lane(self, sqlite_storage,
                                                tmp_path, capsys):
        import datetime as dt

        from predictionio_tpu.data.event import Event

        main(["app", "new", "colsql"])
        app = storage.get_metadata_apps().get_by_name("colsql")
        le = storage.get_levents()
        t0 = dt.datetime(2021, 5, 1, tzinfo=dt.timezone.utc)
        le.insert_batch(
            [Event(event="rate", entity_type="user", entity_id=f"u{i}",
                   target_entity_type="item", target_entity_id=f"i{i % 3}",
                   properties={"rating": float(i % 5)},
                   event_time=t0 + dt.timedelta(seconds=i))
             for i in range(50)], app.id)
        out = str(tmp_path / "events.npz")
        assert main(["export", "--app-name", "colsql", "--output", out,
                     "--format", "columnar"]) == 0
        main(["app", "new", "colsql2"])
        assert main(["import", "--app-name", "colsql2", "--input",
                     out]) == 0
        app2 = storage.get_metadata_apps().get_by_name("colsql2")
        got = list(le.find(app2.id))
        assert len(got) == 50
        assert {e.entity_id for e in got} == {f"u{i}" for i in range(50)}
        assert all(e.properties.get("rating") is not None for e in got)

    def test_columnar_import_validates(self, mem_storage, tmp_path,
                                       capsys):
        """A hand-built container must not bypass event validation."""
        import numpy as np

        from predictionio_tpu.tools import export_import as ei

        arrays = {
            "format_version": np.int64(ei.COLUMNAR_FORMAT_VERSION),
            "n_events": np.int64(1),
            "event_ids": np.asarray(["x"], dtype=np.str_),
            "event_times": np.asarray([0.0]),
            "creation_times": np.asarray([np.nan]),
            "properties": np.asarray([""], dtype=np.str_),
            "tags": np.asarray([""], dtype=np.str_),
        }
        cols = {"events": ["$bogus"], "entity_types": ["user"],
                "entity_ids": ["u1"], "target_entity_types": [None],
                "target_entity_ids": [None], "pr_ids": [None]}
        for name, vals in cols.items():
            codes, labels = ei._dict_encode(vals)
            arrays[f"{name}_codes"] = codes
            arrays[f"{name}_labels"] = labels
        bad = tmp_path / "bad.npz"
        with open(bad, "wb") as f:
            np.savez_compressed(f, **arrays)
        main(["app", "new", "colbad"])
        assert main(["import", "--app-name", "colbad", "--input",
                     str(bad)]) == 1
        err = capsys.readouterr().err
        assert "not a supported reserved event name" in err

    def test_columnar_import_rejects_bad_props_json(self, mem_storage,
                                                    tmp_path, capsys):
        """The raw lane writes property strings verbatim; malformed JSON
        must be rejected up front, not poison later reads."""
        import numpy as np

        from predictionio_tpu.tools import export_import as ei

        arrays = {
            "format_version": np.int64(ei.COLUMNAR_FORMAT_VERSION),
            "n_events": np.int64(1),
            "event_ids": np.asarray(["x"], dtype=np.str_),
            "event_times": np.asarray([1.0]),
            "creation_times": np.asarray([np.nan]),
            "properties": np.asarray(["{not json"], dtype=np.str_),
            "tags": np.asarray([""], dtype=np.str_),
        }
        cols = {"events": ["rate"], "entity_types": ["user"],
                "entity_ids": ["u1"], "target_entity_types": [None],
                "target_entity_ids": [None], "pr_ids": [None]}
        for name, vals in cols.items():
            codes, labels = ei._dict_encode(vals)
            arrays[f"{name}_codes"] = codes
            arrays[f"{name}_labels"] = labels
        bad = tmp_path / "badprops.npz"
        with open(bad, "wb") as f:
            np.savez_compressed(f, **arrays)
        main(["app", "new", "colbadp"])
        assert main(["import", "--app-name", "colbadp", "--input",
                     str(bad)]) == 1
        assert "bad properties JSON" in capsys.readouterr().err

    def test_import_zip_but_not_npz_errors_cleanly(self, mem_storage,
                                                   tmp_path, capsys):
        import zipfile

        z = tmp_path / "events.zip"
        with zipfile.ZipFile(z, "w") as zf:
            zf.writestr("events.jsonl", '{"event":"rate"}\n')
        main(["app", "new", "zipapp"])
        assert main(["import", "--app-name", "zipapp", "--input",
                     str(z)]) == 1
        assert "not a readable columnar" in capsys.readouterr().err

    def _columnar_roundtrip(self, tmp_path):
        """100k-event jsonl vs columnar export/import round trip; returns
        (t_jsonl, t_col, jsonl_path, npz_path, N)."""
        import time

        import numpy as np

        main(["app", "new", "bigexp"])
        app = storage.get_metadata_apps().get_by_name("bigexp")
        le = storage.get_levents()
        rng = np.random.default_rng(0)
        N = 100_000
        rows = [(f"id{i:06d}", "rate", "user",
                 f"u{rng.integers(0, 2000)}", "item",
                 f"i{rng.integers(0, 500)}",
                 '{"rating":%d}' % rng.integers(1, 6),
                 1600000000.0 + i, "[]", None, 1600000000.0)
                for i in range(N)]
        le.init(app.id)
        le.insert_raw_batch(rows, app.id, None)

        jl, npz = str(tmp_path / "e.jsonl"), str(tmp_path / "e.npz")
        t0 = time.perf_counter()
        assert main(["export", "--app-name", "bigexp", "--output",
                     jl]) == 0
        main(["app", "new", "impj"])
        assert main(["import", "--app-name", "impj", "--input", jl]) == 0
        t_jsonl = time.perf_counter() - t0

        t0 = time.perf_counter()
        assert main(["export", "--app-name", "bigexp", "--output", npz,
                     "--format", "columnar"]) == 0
        main(["app", "new", "impc"])
        assert main(["import", "--app-name", "impc", "--input",
                     npz]) == 0
        t_col = time.perf_counter() - t0
        return t_jsonl, t_col, jl, npz, N

    def test_columnar_roundtrip_smaller_at_scale(
            self, sqlite_storage, tmp_path, capsys):
        """The point of the format (EventsToFile.scala:35,94 parquet
        default): at 100k events the columnar file is an order of
        magnitude smaller than jsonl and the round trip is lossless
        (measured at 1M: 7MB vs 243MB). The wall-clock ratio is a
        separate perf-marked test — timing under CI load is noise, the
        file size is the deterministic hard check."""
        _, _, jl, npz, N = self._columnar_roundtrip(tmp_path)

        import os as _os
        assert _os.path.getsize(npz) < _os.path.getsize(jl) / 10
        le = storage.get_levents()
        aj = storage.get_metadata_apps().get_by_name("impj")
        ac = storage.get_metadata_apps().get_by_name("impc")
        nj = sum(1 for _ in le.find(aj.id, limit=-1))
        nc = sum(1 for _ in le.find(ac.id, limit=-1))
        assert nj == nc == N

    @pytest.mark.perf
    @pytest.mark.slow
    def test_columnar_roundtrip_wallclock_ratio(
            self, sqlite_storage, tmp_path, capsys):
        """Perf-only (run with ``-m perf``): the columnar round trip must
        not be catastrophically slower than jsonl (measured 1.6x FASTER
        at 1M; 1.5x is a generous noise margin). Excluded from tier-1 —
        wall-clock ratios flake under parallel CI load."""
        t_jsonl, t_col, _, _, _ = self._columnar_roundtrip(tmp_path)
        assert t_col < t_jsonl * 1.5, (t_col, t_jsonl)

    def test_bad_format_flag(self, mem_storage, tmp_path, capsys):
        main(["app", "new", "fmtapp"])
        import pytest as _pytest
        with _pytest.raises(SystemExit):
            main(["export", "--app-name", "fmtapp", "--output",
                  str(tmp_path / "x"), "--format", "parquet"])


class TestTemplateAndLifecycleVerbs:
    def seed(self, app_name="cliapp", n_users=12):
        import datetime as dt
        import numpy as np
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage.base import App

        aid = storage.get_metadata_apps().insert(App(0, app_name))
        le = storage.get_levents()
        le.init(aid)
        rng = np.random.default_rng(1)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        le.insert_batch([
            Event(event="rate", entity_type="user", entity_id=f"u{u}",
                  target_entity_type="item",
                  target_entity_id=f"i{rng.integers(0, 6)}",
                  properties={"rating": float(rng.integers(1, 6))},
                  event_time=t0)
            for u in range(n_users) for _ in range(5)], aid)
        return aid

    def test_template_list_get_build_train(self, mem_storage, tmp_path,
                                           capsys, monkeypatch):
        import json

        assert main(["template", "list"]) == 0
        assert "recommendation" in capsys.readouterr().out

        engine_dir = tmp_path / "myengine"
        assert main(["template", "get", "recommendation",
                     str(engine_dir)]) == 0
        variant_path = engine_dir / "engine.json"
        assert main(["template", "get", "recommendation",
                     str(engine_dir)]) == 1  # already exists
        assert main(["template", "get", "nope", str(tmp_path / "x")]) == 1
        capsys.readouterr()

        self.seed()
        variant = json.loads(variant_path.read_text())
        variant["datasource"]["params"]["appName"] = "cliapp"
        variant["algorithms"][0]["params"].update(
            {"rank": 4, "numIterations": 2})
        variant_path.write_text(json.dumps(variant))

        assert main(["build", "--engine-variant", str(variant_path)]) == 0
        assert "ready for training" in capsys.readouterr().out

        assert main(["train", "--engine-variant", str(variant_path)]) == 0
        out = capsys.readouterr().out
        assert "Training completed" in out
        iid = out.split("ID:")[-1].strip()
        instance = storage.get_metadata_engine_instances().get(iid)
        assert instance.status == "COMPLETED"
        assert storage.get_model_data_models().get(iid) is not None

    def test_train_stop_after_read(self, mem_storage, tmp_path, capsys):
        import json

        engine_dir = tmp_path / "e2"
        main(["template", "get", "recommendation", str(engine_dir)])
        self.seed("stopapp")
        variant_path = engine_dir / "engine.json"
        variant = json.loads(variant_path.read_text())
        variant["datasource"]["params"]["appName"] = "stopapp"
        variant_path.write_text(json.dumps(variant))
        capsys.readouterr()
        assert main(["train", "--engine-variant", str(variant_path),
                     "--stop-after-read"]) == 0
        assert "interrupted" in capsys.readouterr().out

    def test_build_errors(self, mem_storage, tmp_path, capsys):
        import json

        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"engineFactory": "nope.nope:f"}))
        assert main(["build", "--engine-variant", str(bad)]) == 1
        none = tmp_path / "none.json"
        none.write_text(json.dumps({}))
        assert main(["build", "--engine-variant", str(none)]) == 1

    def test_eval_verb(self, mem_storage, capsys):
        self.seed("evalapp", n_users=10)
        assert main(["eval", "tests.cli_eval_fixture:make_evaluation",
                     "tests.cli_eval_fixture:make_generator"]) == 0
        out = capsys.readouterr().out
        assert "[INFO]" in out
        rows = storage.get_metadata_evaluation_instances().get_completed()
        assert len(rows) == 1
        assert rows[0].evaluation_class == (
            "tests.cli_eval_fixture:make_evaluation")


class TestPrecisionFlags:
    """--precision / --serve-precision plumbing (the CLI arm of the
    ops/als.py + ops/serving.py precision policy) and the bench device
    watchdog's configurable-deadline skip artifact."""

    def test_unknown_precision_value_rejected(self, capsys):
        # argparse choices: a typo'd lane must never reach training.
        # (int8 is serving-only: valid for --serve-precision since
        # PR 11, still rejected for the training-side --precision.)
        with pytest.raises(SystemExit):
            main(["train", "--precision", "fp16"])
        with pytest.raises(SystemExit):
            main(["train", "--precision", "int8"])
        with pytest.raises(SystemExit):
            main(["deploy", "--serve-precision", "fp16"])
        with pytest.raises(SystemExit):
            main(["deploy", "--serve-kernel", "mosaic"])

    def test_train_precision_flag_sets_env(self, mem_storage, tmp_path,
                                           capsys, monkeypatch):
        """--precision bf16 lands in PIO_ALS_PRECISION, the single
        source of truth the per-call resolver reads — so the flag
        affects the very training the command runs."""
        import json
        import os

        # setenv("") (not delenv): cmd_train writes os.environ directly,
        # so monkeypatch must have a recorded value to restore — an
        # empty string resolves to the default lane either way
        monkeypatch.setenv("PIO_ALS_PRECISION", "")
        engine_dir = tmp_path / "precengine"
        assert main(["template", "get", "recommendation",
                     str(engine_dir)]) == 0
        TestTemplateAndLifecycleVerbs().seed("precapp")
        variant_path = engine_dir / "engine.json"
        variant = json.loads(variant_path.read_text())
        variant["datasource"]["params"]["appName"] = "precapp"
        variant_path.write_text(json.dumps(variant))
        capsys.readouterr()
        assert main(["train", "--engine-variant", str(variant_path),
                     "--precision", "bf16"]) == 0
        assert os.environ.get("PIO_ALS_PRECISION") == "bf16"
        assert "Training completed" in capsys.readouterr().out

    def test_serve_precision_flag_sets_env(self, monkeypatch):
        from predictionio_tpu.tools.run_commands import (
            _apply_precision_flags,
        )

        import argparse
        import os

        monkeypatch.setenv("PIO_SERVE_PRECISION", "")
        _apply_precision_flags(argparse.Namespace(serve_precision="bf16"))
        assert os.environ.get("PIO_SERVE_PRECISION") == "bf16"

    def test_bench_watchdog_skip_artifact_is_immediate(self):
        """A probe that FAILS fast (dead tunnel refusing, not hanging)
        must emit the skip artifact immediately — not burn the full
        PIO_BENCH_DEVICE_TIMEOUT deadline, and not exit artifact-less
        (BENCH_r05 regression)."""
        import json
        import os
        import subprocess
        import sys
        import time

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "bogus"  # backend init raises fast
        env["PIO_BENCH_DEVICE_TIMEOUT"] = "120"
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-c",
             "import bench; bench._device_watchdog()"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))),
            env=env, capture_output=True, text=True, timeout=110)
        took = time.monotonic() - t0
        assert proc.returncode == 3
        assert took < 60, f"skip artifact took {took:.0f}s"
        artifact = json.loads(proc.stdout.strip().splitlines()[-1])
        assert artifact["metric"] == \
            "als_implicit_ml100k_rank64_events_per_sec"
        assert artifact["value"] == 0
        assert "failed immediately" in artifact["error"]

    def test_bench_watchdog_timeout_env_override(self, monkeypatch):
        """PIO_BENCH_DEVICE_TIMEOUT configures the hang deadline; a
        healthy backend returns well inside it."""
        import bench

        monkeypatch.setenv("PIO_BENCH_DEVICE_TIMEOUT", "45")
        bench._device_watchdog()  # healthy CPU backend: returns
