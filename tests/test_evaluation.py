"""Evaluation & tuning tests.

Mirrors the reference coverage: MetricTest (stats over eval sets),
MetricEvaluatorTest (best selection), EvaluationTest (engine/evaluator
coupling), FastEvalEngineTest (per-prefix cache hit counts).
"""

import dataclasses
import json
import math
import threading

import pytest

from predictionio_tpu.controller import (
    ComputeContext,
    Engine,
    EngineParams,
)
from predictionio_tpu.controller.evaluation import (
    EngineParamsGenerator,
    Evaluation,
    MetricEvaluator,
    MetricEvaluatorResult,
)
from predictionio_tpu.controller.fast_eval import FastEvalEngine
from predictionio_tpu.controller.metrics import (
    AverageMetric,
    OptionAverageMetric,
    OptionStdevMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
)
from tests.dase_fixtures import (
    DataSource0,
    IdParams,
    PAlgo0,
    Preparator0,
    Serving0,
)

CTX = ComputeContext(_devices=("cpu0",))


# ---------------------------------------------------------------------------
# Metrics (Metric.scala:96-244 semantics)
# ---------------------------------------------------------------------------

class QMetric(AverageMetric):
    """Score = the query's numeric payload (MetricTest's Metric0 style)."""

    def calculate_qpa(self, q, p, a):
        return float(q)


class QOptionMetric(OptionAverageMetric):
    def calculate_qpa(self, q, p, a):
        return None if q is None else float(q)


class QStdev(StdevMetric):
    def calculate_qpa(self, q, p, a):
        return float(q)


class QOptionStdev(OptionStdevMetric):
    def calculate_qpa(self, q, p, a):
        return None if q is None else float(q)


class QSum(SumMetric):
    def calculate_qpa(self, q, p, a):
        return int(q)


def eval_sets(*groups):
    """[(EI, [(q, None, None) ...])] from raw per-set score lists."""
    return [(i, [(q, None, None) for q in qs])
            for i, qs in enumerate(groups)]


def test_average_metric_spans_eval_sets():
    data = eval_sets([1, 2, 3], [5])
    assert QMetric().calculate(CTX, data) == pytest.approx(11 / 4)


def test_option_average_skips_none():
    data = eval_sets([1, None, 3], [None])
    assert QOptionMetric().calculate(CTX, data) == pytest.approx(2.0)


def test_stdev_is_population_stdev():
    data = eval_sets([2, 4, 4, 4], [5, 5, 7, 9])
    assert QStdev().calculate(CTX, data) == pytest.approx(2.0)


def test_option_stdev_skips_none():
    data = eval_sets([2, None, 4, 4, 4], [5, 5, None, 7, 9])
    assert QOptionStdev().calculate(CTX, data) == pytest.approx(2.0)


def test_sum_metric_keeps_type():
    data = eval_sets([1, 2], [3])
    assert QSum().calculate(CTX, data) == 6


def test_zero_metric():
    assert ZeroMetric().calculate(CTX, eval_sets([1, 2])) == 0.0


def test_metric_compare_default_bigger_wins():
    m = QMetric()
    assert m.compare(2.0, 1.0) > 0
    assert m.compare(1.0, 1.0) == 0
    assert m.compare(0.0, 1.0) < 0


# ---------------------------------------------------------------------------
# MetricEvaluator (MetricEvaluator.scala:215-246)
# ---------------------------------------------------------------------------

class DSIdMetric(AverageMetric):
    """Scores eval output by the data-source id stamped into the query."""

    def calculate_qpa(self, q, p, a):
        return float(q.id)


def grid_engine():
    return Engine(DataSource0, Preparator0, {"": PAlgo0}, Serving0)


def grid_params(ds_ids):
    return [EngineParams(
        data_source_params=("", IdParams(i, en=1, qn=2)),
        preparator_params=("", IdParams(0)),
        algorithm_params_list=[("", IdParams(0))],
        serving_params=("", IdParams(0)),
    ) for i in ds_ids]


def test_metric_evaluator_picks_best(tmp_path):
    engine = grid_engine()
    params_list = grid_params([3, 7, 5])
    eval_data = engine.batch_eval(CTX, params_list)
    out = str(tmp_path / "best.json")
    evaluator = MetricEvaluator(DSIdMetric(), output_path=out)
    result = evaluator.evaluate_base(CTX, None, eval_data, None)

    assert isinstance(result, MetricEvaluatorResult)
    assert result.best_idx == 1
    assert result.best_score.score == pytest.approx(7.0)
    assert result.best_engine_params is params_list[1]
    assert result.metric_header == "DSIdMetric"
    assert "Best Params Index: 1" in result.to_one_liner()

    # best.json is a loadable variant snapshot (saveEngineJson :190-213)
    variant = json.loads(open(out).read())
    assert variant["datasource"]["params"]["id"] == 7
    # and it round-trips through the engine's variant parser
    ep = engine.engine_params_from_variant(variant)
    assert ep.data_source_params[1].id == 7


def test_metric_evaluator_tie_keeps_first():
    engine = grid_engine()
    params_list = grid_params([4, 4])
    eval_data = engine.batch_eval(CTX, params_list)
    result = MetricEvaluator(DSIdMetric()).evaluate_base(
        CTX, None, eval_data, None)
    assert result.best_idx == 0
    assert result.output_path is None


def test_metric_evaluator_other_metrics():
    engine = grid_engine()
    eval_data = engine.batch_eval(CTX, grid_params([2]))
    result = MetricEvaluator(DSIdMetric(), [ZeroMetric()]).evaluate_base(
        CTX, None, eval_data, None)
    assert result.other_metric_headers == ["ZeroMetric"]
    assert list(result.best_score.other_scores) == [0.0]
    parsed = json.loads(result.to_json())
    assert parsed["bestScore"]["score"] == pytest.approx(2.0)
    assert "<table>" in result.to_html()


# ---------------------------------------------------------------------------
# Evaluation / EngineParamsGenerator (Evaluation.scala, EngineParamsGenerator.scala)
# ---------------------------------------------------------------------------

def test_evaluation_engine_metric_implies_best_json():
    ev = Evaluation()
    ev.engine_metric = (grid_engine(), DSIdMetric())
    engine, evaluator = ev.engine_evaluator
    assert isinstance(evaluator, MetricEvaluator)
    assert evaluator.output_path == "best.json"


def test_evaluation_set_once():
    ev = Evaluation()
    ev.engine_metrics = (grid_engine(), DSIdMetric(), [ZeroMetric()])
    assert ev.evaluator.output_path is None
    with pytest.raises(AssertionError):
        ev.engine_metric = (grid_engine(), DSIdMetric())


def test_evaluation_unset_raises():
    with pytest.raises(AssertionError):
        Evaluation().engine


def test_engine_params_generator_set_once():
    gen = EngineParamsGenerator()
    with pytest.raises(AssertionError):
        gen.engine_params_list
    gen.engine_params_list = grid_params([1, 2])
    assert len(gen.engine_params_list) == 2
    with pytest.raises(AssertionError):
        gen.engine_params_list = []


# ---------------------------------------------------------------------------
# FastEvalEngine memoization (FastEvalEngine.scala:50-342)
# ---------------------------------------------------------------------------

class CountingDataSource(DataSource0):
    reads = 0

    def read_eval(self, ctx):
        type(self).reads += 1
        return super().read_eval(ctx)


class CountingPreparator(Preparator0):
    prepares = 0

    def prepare(self, ctx, td):
        type(self).prepares += 1
        return super().prepare(ctx, td)


class CountingAlgo(PAlgo0):
    trains = 0

    def train(self, ctx, pd):
        type(self).trains += 1
        return super().train(ctx, pd)


@pytest.fixture(autouse=True)
def _reset_counters():
    CountingDataSource.reads = 0
    CountingPreparator.prepares = 0
    CountingAlgo.trains = 0
    yield


def fast_engine():
    return FastEvalEngine(CountingDataSource, CountingPreparator,
                          {"": CountingAlgo}, Serving0)


def fe_params(ds=1, prep=2, algo=3, serving=9):
    return EngineParams(
        data_source_params=("", IdParams(ds, en=2, qn=2)),
        preparator_params=("", IdParams(prep)),
        algorithm_params_list=[("", IdParams(algo))],
        serving_params=("", IdParams(serving)),
    )


def test_fast_eval_shares_datasource_and_preparator():
    """Varying only algo params: DS reads once, preparator runs once per
    eval set, algorithms once per distinct algo params."""
    engine = fast_engine()
    result = engine.batch_eval(
        CTX, [fe_params(algo=3), fe_params(algo=4), fe_params(algo=3)])
    assert len(result) == 3
    assert CountingDataSource.reads == 1
    assert CountingPreparator.prepares == 2      # 2 eval sets, one pass
    assert CountingAlgo.trains == 4              # 2 algo params x 2 eval sets


def test_fast_eval_shares_algorithms_across_serving():
    engine = fast_engine()
    engine.batch_eval(
        CTX, [fe_params(serving=1), fe_params(serving=2)])
    assert CountingDataSource.reads == 1
    assert CountingAlgo.trains == 2              # 1 algo params x 2 eval sets


def test_fast_eval_distinct_datasource_recomputes():
    engine = fast_engine()
    engine.batch_eval(CTX, [fe_params(ds=1), fe_params(ds=2)])
    assert CountingDataSource.reads == 2
    assert CountingPreparator.prepares == 4


def test_fast_eval_output_matches_slow_engine():
    """FastEvalEngine must produce the same (Q, P, A) stream as Engine.eval
    modulo the documented no-supplement quirk (none of these fixtures
    supplement)."""
    slow = Engine(DataSource0, Preparator0, {"": PAlgo0}, Serving0)
    fast = FastEvalEngine(DataSource0, Preparator0, {"": PAlgo0}, Serving0)
    ep = EngineParams(
        data_source_params=("", IdParams(1, en=2, qn=3)),
        preparator_params=("", IdParams(2)),
        algorithm_params_list=[("", IdParams(3))],
        serving_params=("", IdParams(9)),
    )
    slow_out = slow.eval(CTX, ep)
    fast_out = fast.eval(CTX, ep)
    assert len(slow_out) == len(fast_out) == 2
    for (ei_s, qpa_s), (ei_f, qpa_f) in zip(slow_out, fast_out):
        assert ei_s == ei_f
        assert [(q, a) for q, _p, a in qpa_s] == [
            (q, a) for q, _p, a in qpa_f]
        assert [p.id for _q, p, _a in qpa_s] == [
            p.id for _q, p, _a in qpa_f]


def test_fast_eval_single_eval_unwraps():
    engine = fast_engine()
    out = engine.eval(CTX, fe_params())
    assert len(out) == 2  # en=2 eval sets


# ---------------------------------------------------------------------------
# Parallel tuning (.par analog, MetricEvaluator.scala:221-230 /
# FastEvalEngine.scala:176) + bounded FastEval caches
# ---------------------------------------------------------------------------

class OverlapDataSource(DataSource0):
    """Records concurrent read_eval occupancy to prove the sweep
    overlaps param sets."""

    active = 0
    max_active = 0
    _lock = threading.Lock()

    def read_eval(self, ctx):
        import time

        cls = type(self)
        with cls._lock:
            cls.active += 1
            cls.max_active = max(cls.max_active, cls.active)
        try:
            time.sleep(0.05)
            return super().read_eval(ctx)
        finally:
            with cls._lock:
                cls.active -= 1


def test_batch_eval_overlaps_param_sets():
    """Engine.batch_eval runs param sets concurrently (each has a
    distinct datasource so nothing serializes on memoization)."""
    from predictionio_tpu.core.base import WorkflowParams

    OverlapDataSource.active = OverlapDataSource.max_active = 0
    engine = Engine(OverlapDataSource, Preparator0, {"": PAlgo0}, Serving0)
    eps = [fe_params(ds=i) for i in range(4)]
    out = engine.batch_eval(CTX, eps,
                            WorkflowParams(eval_parallelism=4))
    assert len(out) == 4
    # results stay ordered by input
    assert [ep.data_source_params[1].id for ep, _ in out] == [0, 1, 2, 3]
    assert OverlapDataSource.max_active >= 2


def test_batch_eval_serial_when_parallelism_one():
    from predictionio_tpu.core.base import WorkflowParams

    OverlapDataSource.active = OverlapDataSource.max_active = 0
    engine = Engine(OverlapDataSource, Preparator0, {"": PAlgo0}, Serving0)
    engine.batch_eval(CTX, [fe_params(ds=i) for i in range(3)],
                      WorkflowParams(eval_parallelism=1))
    assert OverlapDataSource.max_active == 1


def test_fast_eval_parallel_still_computes_prefixes_once():
    """Under a parallel sweep, racing param sets that share a prefix
    serialize on the per-key lock: exactly one compute."""
    from predictionio_tpu.core.base import WorkflowParams

    engine = fast_engine()
    result = engine.batch_eval(
        CTX, [fe_params(algo=a) for a in (3, 4, 3, 4, 3)],
        WorkflowParams(eval_parallelism=4))
    assert len(result) == 5
    assert CountingDataSource.reads == 1
    assert CountingPreparator.prepares == 2
    assert CountingAlgo.trains == 4  # 2 distinct algo params x 2 eval sets


def test_fast_eval_cache_is_bounded():
    """LRU caps each prefix cache (round-3 verdict weak #5: the
    reference keeps every trained model alive for the whole sweep)."""
    from predictionio_tpu.controller.fast_eval import FastEvalEngineWorkflow
    from predictionio_tpu.core.base import WorkflowParams

    engine = fast_engine()
    engine.cache_size = 2
    captured = {}
    orig_get = FastEvalEngineWorkflow.get

    def capture_get(self, eps, workers=1):
        captured["wf"] = self
        return orig_get(self, eps, workers)

    FastEvalEngineWorkflow.get = capture_get
    try:
        engine.batch_eval(CTX, [fe_params(ds=i) for i in range(5)],
                          WorkflowParams(eval_parallelism=1))
    finally:
        FastEvalEngineWorkflow.get = orig_get
    wf = captured["wf"]
    assert len(wf.data_source_cache) <= 2
    assert len(wf.preparator_cache) <= 2
    assert len(wf.algorithms_cache) <= 2
    assert len(wf.serving_cache) <= 2
    assert CountingDataSource.reads == 5  # distinct ds: no sharing possible


def test_metric_evaluator_parallel_scoring_matches_serial():
    from predictionio_tpu.core.base import WorkflowParams

    engine = Engine(DataSource0, Preparator0, {"": PAlgo0}, Serving0)
    eps = [fe_params(ds=i) for i in range(3)]
    data = engine.batch_eval(CTX, eps)
    ev = MetricEvaluator(DSIdMetric())
    serial = ev.evaluate_base(CTX, None, data,
                              WorkflowParams(eval_parallelism=1))
    parallel = ev.evaluate_base(CTX, None, data,
                                WorkflowParams(eval_parallelism=4))
    assert serial.best_idx == parallel.best_idx
    assert [s.score for _, s in serial.engine_params_scores] == \
        [s.score for _, s in parallel.engine_params_scores]


# ---------------------------------------------------------------------------
# tune -> train handoff (best.json engineFactory round trip)
# ---------------------------------------------------------------------------

class HandoffEval(Evaluation):
    """Module-level Evaluation so load_engine_factory can resolve it."""

    def __init__(self):
        super().__init__()
        self.engine_evaluator = (grid_engine(), MetricEvaluator(DSIdMetric()))


def test_best_json_engine_factory_is_trainable(tmp_path, mem_storage):
    """best.json's engineFactory must load through create_workflow and
    train (the advertised tune-then-train handoff)."""
    from predictionio_tpu.workflow import WorkflowConfig, create_workflow

    ev = HandoffEval()
    out = str(tmp_path / "best.json")
    evaluator = MetricEvaluator(DSIdMetric(), output_path=out)
    eval_data = ev.engine.batch_eval(CTX, grid_params([2, 6]))
    evaluator.evaluate_base(CTX, ev, eval_data, None)

    variant = json.loads(open(out).read())
    assert variant["engineFactory"] == f"{__name__}:HandoffEval"
    iid = create_workflow(
        WorkflowConfig(engine_factory=variant["engineFactory"]),
        variant=variant)
    assert iid is not None


def test_metric_evaluator_rejects_empty_grid():
    with pytest.raises(ValueError, match="at least one"):
        MetricEvaluator(DSIdMetric()).evaluate_base(CTX, None, [], None)
