"""Engine train/eval dataflow wiring tests.

Mirrors the assertions of the reference ``EngineTest``/``EngineTrainSuite``/
``EngineEvalSuite`` (core/src/test/.../controller/) using identity-encoding
stubs from dase_fixtures.
"""

import dataclasses

import pytest

from predictionio_tpu.controller import (
    ComputeContext,
    Engine,
    EngineConfigError,
    EngineParams,
    RETRAIN,
    PersistentModelManifest,
    SimpleEngine,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    WorkflowParams,
)
from tests.dase_fixtures import (
    Actual,
    AlgoModel,
    DataSource0,
    FailingDataSource,
    IdParams,
    LAlgo0,
    P2LAlgo0,
    PAlgo0,
    PersistedModel,
    PersistentAlgo,
    Preparator0,
    Prediction,
    ProcessedData,
    Query,
    Serving0,
    SupplementingServing,
    TrainingData,
    UnsavablePersistedModel,
)

CTX = ComputeContext(_devices=("cpu0",))  # no jax needed for wiring tests


def make_engine(algos=None, serving=Serving0, ds=DataSource0):
    return Engine(ds, Preparator0, algos or {"": PAlgo0}, serving)


def ep(ds_id=1, prep_id=2, algos=(("", 3),), serving_id=9, **ds_kw):
    return EngineParams(
        data_source_params=("", IdParams(ds_id, **ds_kw)),
        preparator_params=("", IdParams(prep_id)),
        algorithm_params_list=[(n, IdParams(i)) for n, i in algos],
        serving_params=("", IdParams(serving_id)),
    )


class TestTrain:
    def test_single_algo_dataflow(self):
        engine = make_engine()
        models = engine.train(CTX, ep(), "inst0", WorkflowParams())
        # PAlgorithm without PersistentModel -> RETRAIN persisted form
        assert models == [RETRAIN]

    def test_p2l_models_flow_through(self):
        engine = make_engine({"": P2LAlgo0})
        models = engine.train(CTX, ep(ds_id=7, prep_id=8, algos=(("", 5),)),
                              "inst0")
        assert models == [
            AlgoModel(5, ProcessedData(8, TrainingData(7)))]

    def test_multi_algo_order_and_params(self):
        engine = make_engine({"a": P2LAlgo0, "b": LAlgo0})
        models = engine.train(
            CTX, ep(algos=(("a", 10), ("b", 11), ("a", 12))), "i")
        assert [m.id for m in models] == [10, 11, 12]
        # every algorithm saw the same prepared data
        assert all(m.pd == ProcessedData(2, TrainingData(1)) for m in models)

    def test_requires_algorithms(self):
        engine = make_engine()
        with pytest.raises(EngineConfigError, match="at least 1"):
            engine.train(CTX, EngineParams(algorithm_params_list=[]), "i")

    def test_unknown_algo_name(self):
        engine = make_engine()
        with pytest.raises(EngineConfigError, match="not registered"):
            engine.train(CTX, ep(algos=(("nope", 1),)), "i")

    def test_sanity_check_failure(self):
        engine = make_engine(ds=FailingDataSource)
        with pytest.raises(AssertionError, match="Not Error"):
            engine.train(CTX, ep(), "i")
        # skip_sanity_check bypasses it (Engine.scala:634-638)
        engine.train(CTX, ep(), "i",
                     WorkflowParams(skip_sanity_check=True))

    def test_stop_after_read_and_prepare(self):
        engine = make_engine()
        with pytest.raises(StopAfterReadInterruption):
            engine.train(CTX, ep(), "i", WorkflowParams(stop_after_read=True))
        with pytest.raises(StopAfterPrepareInterruption):
            engine.train(CTX, ep(), "i",
                         WorkflowParams(stop_after_prepare=True))


class TestPersistence:
    def test_persistent_model_saved_and_manifested(self):
        PersistedModel.store.clear()
        engine = make_engine({"": PersistentAlgo})
        models = engine.train(CTX, ep(algos=(("", 4),)), "inst7")
        assert isinstance(models[0], PersistentModelManifest)
        assert "PersistedModel" in models[0].class_path
        assert "inst7-0-" in next(iter(PersistedModel.store))

    def test_prepare_deploy_loads_manifest(self):
        PersistedModel.store.clear()
        engine = make_engine({"": PersistentAlgo})
        params = ep(algos=(("", 4),))
        persisted = engine.train(CTX, params, "inst8")
        out = engine.prepare_deploy(CTX, params, "inst8", persisted)
        assert isinstance(out[0], PersistedModel)
        assert out[0].id == 4

    def test_prepare_deploy_retrains_retrain_sentinel(self):
        engine = make_engine({"": PAlgo0})
        params = ep(ds_id=1, prep_id=2, algos=(("", 3),))
        persisted = engine.train(CTX, params, "inst9")
        assert persisted == [RETRAIN]
        out = engine.prepare_deploy(CTX, params, "inst9", persisted)
        # model was re-trained from the data source (Engine.scala:208-230)
        assert out == [AlgoModel(3, ProcessedData(2, TrainingData(1)))]

    def test_unsavable_persistent_model_becomes_retrain(self):
        class Algo(PersistentAlgo):
            def train(self, ctx, pd):
                return UnsavablePersistedModel(self.params.id)

        engine = make_engine({"": Algo})
        persisted = engine.train(CTX, ep(), "i")
        assert persisted == [RETRAIN]

    def test_mismatched_model_count(self):
        engine = make_engine()
        with pytest.raises(EngineConfigError, match="persisted models"):
            engine.prepare_deploy(CTX, ep(), "i", [RETRAIN, RETRAIN])


class TestEval:
    def test_eval_dataflow(self):
        engine = make_engine({"a": PAlgo0, "b": P2LAlgo0})
        params = EngineParams(
            data_source_params=("", IdParams(1, en=2, qn=3)),
            preparator_params=("", IdParams(2)),
            algorithm_params_list=[("a", 4), ("b", 5)] and
            [("a", IdParams(4)), ("b", IdParams(5))],
            serving_params=("", IdParams(9)),
        )
        results = engine.eval(CTX, params)
        assert len(results) == 2  # en eval sets
        for ex, (eval_info, qpa) in enumerate(results):
            assert eval_info.id == 1
            assert len(qpa) == 3  # qn queries
            for qx, (q, p, a) in enumerate(qpa):
                assert q == Query(1, ex=ex, qx=qx)
                assert a == Actual(1, ex=ex, qx=qx)
                # serve saw predictions in algorithm order
                assert [pp.id for pp in p.ps] == [4, 5]
                # every algorithm trained on the same prepared data
                assert all(
                    pp.model == AlgoModel(pp.id,
                                          ProcessedData(2, TrainingData(1)))
                    for pp in p.ps)

    def test_supplement_reaches_predict_not_serve(self):
        engine = make_engine({"": PAlgo0}, serving=SupplementingServing)
        params = EngineParams(
            data_source_params=("", IdParams(1, en=1, qn=2)),
            preparator_params=("", IdParams(2)),
            algorithm_params_list=[("", IdParams(3))],
            serving_params=("", IdParams(9)),
        )
        [(_, qpa)] = engine.eval(CTX, params)
        for q, p, _a in qpa:
            assert q.supp is False          # original query served
            assert p.q.supp is True         # predict saw supplemented query
            assert p.ps[0].q.supp is True

    def test_batch_eval_returns_params_pairs(self):
        engine = make_engine({"": PAlgo0})
        ps = [EngineParams(
                  data_source_params=("", IdParams(i, en=1, qn=1)),
                  preparator_params=("", IdParams(0)),
                  algorithm_params_list=[("", IdParams(0))],
                  serving_params=("", IdParams(0)))
              for i in (1, 2)]
        out = engine.batch_eval(CTX, ps)
        assert [epp.data_source_params[1].id for epp, _ in out] == [1, 2]
        assert [r[0][0].id for _, r in out] == [1, 2]


class TestVariantParams:
    def test_variant_extraction(self):
        engine = make_engine({"als": PAlgo0, "nb": P2LAlgo0})
        params = engine.engine_params_from_variant({
            "datasource": {"params": {"id": 1, "en": 2}},
            "preparator": {"params": {"id": 5}},
            "algorithms": [
                {"name": "als", "params": {"id": 7}},
                {"name": "nb", "params": {"id": 8, "qn": 1}},
            ],
            "serving": {"params": {"id": 9}},
        })
        assert params.data_source_params == ("", IdParams(1, en=2))
        assert params.preparator_params == ("", IdParams(5))
        assert params.algorithm_params_list == [
            ("als", IdParams(7)), ("nb", IdParams(8, qn=1))]
        assert params.serving_params == ("", IdParams(9))

    def test_unknown_param_rejected(self):
        engine = make_engine()
        with pytest.raises(EngineConfigError, match="unknown param"):
            engine.engine_params_from_variant(
                {"datasource": {"params": {"id": 1, "bogus": 2}}})

    def test_missing_required_param_rejected(self):
        engine = make_engine()
        with pytest.raises(EngineConfigError, match="missing required"):
            engine.engine_params_from_variant(
                {"datasource": {"params": {"en": 2}}})

    def test_unknown_algorithm_name_rejected(self):
        engine = make_engine()
        with pytest.raises(EngineConfigError, match="not registered"):
            engine.engine_params_from_variant(
                {"datasource": {"params": {"id": 1}},
                 "algorithms": [{"name": "zzz", "params": {}}]})

    def test_bare_params_block(self):
        # bare {...} without name/params wrapper binds to the "" controller
        engine = make_engine()
        params = engine.engine_params_from_variant(
            {"datasource": {"id": 3}})
        assert params.data_source_params == ("", IdParams(3))


class TestSimpleEngine:
    def test_wiring(self):
        engine = SimpleEngine(DataSource0, P2LAlgo0)
        params = EngineParams(
            data_source_params=("", IdParams(1, en=1, qn=1)),
            algorithm_params_list=[("", IdParams(3))],
        )
        models = engine.train(CTX, params, "i")
        # identity preparator passes TrainingData straight through
        assert models == [AlgoModel(3, TrainingData(1))]
        [(_, qpa)] = engine.eval(CTX, params)
        [(q, p, a)] = qpa
        assert isinstance(p, Prediction) and p.id == 3  # first serving
