"""Event model + validation rules (parity: EventValidation, Event.scala:109-177)."""

import datetime as dt

import pytest

from predictionio_tpu.data.event import (
    Event, EventValidationError, validate_event, is_reserved_prefix,
)


def ev(**kw):
    base = dict(event="rate", entity_type="user", entity_id="u1")
    base.update(kw)
    return Event(**base)


class TestValidation:
    def test_valid_plain_event(self):
        validate_event(ev())

    def test_valid_target_event(self):
        validate_event(ev(target_entity_type="item", target_entity_id="i1"))

    def test_valid_set(self):
        validate_event(ev(event="$set", properties={"a": 1}))

    def test_empty_event_name(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(event=""))

    def test_empty_entity_type(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(entity_type=""))

    def test_empty_entity_id(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(entity_id=""))

    def test_target_fields_must_come_together(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(target_entity_type="item"))
        with pytest.raises(EventValidationError):
            validate_event(ev(target_entity_id="i1"))

    def test_empty_target_strings(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(target_entity_type="", target_entity_id="i1"))

    def test_unset_requires_properties(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(event="$unset"))
        validate_event(ev(event="$unset", properties={"a": 1}))

    def test_reserved_prefix_event_names(self):
        for name in ("$foo", "pio_foo"):
            with pytest.raises(EventValidationError):
                validate_event(ev(event=name))
        validate_event(ev(event="$delete"))

    def test_special_event_cannot_have_target(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(event="$set", properties={"a": 1},
                              target_entity_type="item",
                              target_entity_id="i1"))

    def test_reserved_entity_type(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(entity_type="pio_user"))
        validate_event(ev(entity_type="pio_pr"))  # built-in

    def test_reserved_target_entity_type(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(target_entity_type="pio_x",
                              target_entity_id="1"))

    def test_reserved_property_names(self):
        with pytest.raises(EventValidationError):
            validate_event(ev(properties={"pio_score": 1}))
        with pytest.raises(EventValidationError):
            validate_event(ev(properties={"$score": 1}))

    def test_is_reserved_prefix(self):
        assert is_reserved_prefix("$x")
        assert is_reserved_prefix("pio_x")
        assert not is_reserved_prefix("x")


class TestWireFormat:
    def test_roundtrip(self):
        e = ev(target_entity_type="item", target_entity_id="i7",
               properties={"rating": 4.5}, tags=("a", "b"), pr_id="pk1")
        e2 = Event.from_json(e.to_json())
        assert e2.event == e.event
        assert e2.entity_id == e.entity_id
        assert e2.target_entity_id == "i7"
        assert e2.properties.get("rating", float) == 4.5
        assert e2.tags == ("a", "b")
        assert e2.pr_id == "pk1"
        assert e2.event_time == e.event_time

    def test_from_dict_requires_core_fields(self):
        with pytest.raises(EventValidationError):
            Event.from_dict({"event": "rate"})

    def test_millis_timestamp_accepted(self):
        e = Event.from_dict({"event": "rate", "entityType": "user",
                             "entityId": "u1", "eventTime": 1000.0})
        assert e.event_time == dt.datetime(1970, 1, 1, 0, 0, 1,
                                           tzinfo=dt.timezone.utc)

    def test_naive_times_become_utc(self):
        e = ev(event_time=dt.datetime(2020, 1, 1))
        assert e.event_time.tzinfo is not None

    def test_iso8601_variants_accepted(self):
        """Z suffix, odd fractional-second widths and colon-less offsets
        must parse on every Python (3.10's fromisoformat rejects them;
        the shared compat helper normalizes — utils/compat.py)."""
        utc = dt.timezone.utc
        cases = {
            "2021-06-01T12:30:45Z":
                dt.datetime(2021, 6, 1, 12, 30, 45, tzinfo=utc),
            "2021-06-01T12:30:45.1Z":
                dt.datetime(2021, 6, 1, 12, 30, 45, 100000, tzinfo=utc),
            "2021-06-01T12:30:45.1234567+00:00":
                dt.datetime(2021, 6, 1, 12, 30, 45, 123456, tzinfo=utc),
            "2021-06-01T12:30:45+0530":
                dt.datetime(2021, 6, 1, 12, 30, 45, tzinfo=dt.timezone(
                    dt.timedelta(hours=5, minutes=30))),
        }
        for raw, want in cases.items():
            e = Event.from_dict({"event": "rate", "entityType": "user",
                                 "entityId": "u1", "eventTime": raw})
            assert e.event_time == want, raw
        with pytest.raises(EventValidationError):
            Event.from_dict({"event": "rate", "entityType": "user",
                             "entityId": "u1", "eventTime": "not-a-time"})

    def test_datamap_datetime_accepts_z_suffix(self):
        from predictionio_tpu.data.datamap import DataMap, DataMapError

        dm = DataMap({"t": "2021-06-01T12:30:45Z", "bad": "nope"})
        assert dm.get("t", dt.datetime) == dt.datetime(
            2021, 6, 1, 12, 30, 45, tzinfo=dt.timezone.utc)
        with pytest.raises(DataMapError):
            dm.get("bad", dt.datetime)
