"""Regression template tests (experimental scala-local-regression parity):
OLS fit, the n/k row-dropping Preparator, MSE eval, and the full
train->deploy->query lifecycle of a second L-flavor engine."""

import http.client
import json

import numpy as np
import pytest

from predictionio_tpu.controller import ComputeContext, EngineParams
from predictionio_tpu.core.base import WorkflowParams
from predictionio_tpu.templates.regression import (
    DataSourceParams,
    MeanSquareError,
    PreparatorParams,
    Query,
    engine_factory,
)

CTX = ComputeContext()


@pytest.fixture
def data_file(tmp_path):
    """y = 2*x1 - 3*x2 + 0.5*x3, tiny noise."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(80, 3))
    y = X @ np.asarray([2.0, -3.0, 0.5]) + rng.normal(scale=0.01, size=80)
    f = tmp_path / "lr_data.txt"
    f.write_text("\n".join(
        f"{yi} " + " ".join(str(v) for v in row)
        for yi, row in zip(y, X)))
    return str(f)


def make_params(data_file, n=0, k=0):
    return EngineParams(
        data_source_params=("", DataSourceParams(filepath=data_file)),
        preparator_params=("", PreparatorParams(n=n, k=k)),
    )


class TestRegression:
    def test_recovers_coefficients(self, data_file):
        engine = engine_factory()
        params = make_params(data_file)
        [model] = engine.train(CTX, params)
        np.testing.assert_allclose(model, [2.0, -3.0, 0.5], atol=0.01)
        algo = engine._algorithms(params)[0]
        pred = algo.predict(model, Query(features=(1.0, 1.0, 2.0)))
        assert abs(pred - (2.0 - 3.0 + 1.0)) < 0.05

    def test_preparator_drops_rows(self, data_file):
        engine = engine_factory()
        params = make_params(data_file, n=2, k=0)
        ds = engine._make(engine.data_source_class_map, "",
                          params.data_source_params[1], "ds")
        prep = engine._make(engine.preparator_class_map, "",
                            params.preparator_params[1], "prep")
        td = ds.read_training_base(CTX)
        pd = prep.prepare_base(CTX, td)
        assert len(pd.y) == len(td.y) // 2  # every even index dropped
        # still fits fine on half the data
        [model] = engine.train(CTX, params)
        np.testing.assert_allclose(model, [2.0, -3.0, 0.5], atol=0.02)

    def test_eval_mse_near_zero(self, data_file):
        engine = engine_factory()
        params = make_params(data_file, n=2, k=0)
        results = engine.eval(CTX, params, WorkflowParams())
        mse = MeanSquareError().calculate(CTX, results)
        assert 0 <= mse < 0.01
        # smaller error must win the tuning comparison
        assert MeanSquareError().compare(0.001, 0.5) > 0

    def test_lifecycle_through_query_server(self, mem_storage, data_file):
        from predictionio_tpu.workflow import (
            QueryServer, ServerConfig, run_train,
        )
        from predictionio_tpu.workflow.create_workflow import (
            WorkflowConfig, new_engine_instance,
        )

        engine = engine_factory()
        params = make_params(data_file)
        cfg = WorkflowConfig(
            engine_factory="predictionio_tpu.templates.regression"
                           ":engine_factory")
        iid = run_train(engine, params, new_engine_instance(cfg, params),
                        ctx=CTX)
        assert iid is not None
        srv = QueryServer(ServerConfig(ip="127.0.0.1", port=0)).start(
            undeploy_stale=False)
        try:
            host, port = srv.address
            conn = http.client.HTTPConnection(host, port, timeout=30)
            conn.request("POST", "/queries.json",
                         body=json.dumps({"features": [1.0, 0.0, 0.0]}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            value = json.loads(resp.read().decode())
            conn.close()
            assert resp.status == 200
            assert abs(float(value) - 2.0) < 0.05
        finally:
            srv.stop()
