"""Random-forest tests: the e2 library (MLlib RandomForest.trainClassifier
capability) and the classification template's RandomForestAlgorithm
(add-algorithm/src/main/scala/RandomForestAlgorithm.scala)."""

import numpy as np
import pytest

from predictionio_tpu.e2.forest import (
    RandomForestModel,
    train_classifier,
)


def blobs(n=300, seed=0):
    """Two separable gaussian blobs in 3D."""
    rng = np.random.default_rng(seed)
    X0 = rng.normal(loc=(0, 0, 0), scale=0.7, size=(n // 2, 3))
    X1 = rng.normal(loc=(3, 3, 0), scale=0.7, size=(n // 2, 3))
    X = np.vstack([X0, X1])
    y = np.asarray([0] * (n // 2) + [1] * (n // 2))
    return X, y


class TestForestLibrary:
    def test_learns_separable_blobs(self):
        X, y = blobs()
        m = train_classifier(X, y, num_classes=2, num_trees=10,
                             max_depth=4, seed=1)
        acc = (m.predict_batch(X) == y).mean()
        assert acc > 0.97
        # single predict agrees with batch
        assert m.predict(X[0]) == m.predict_batch(X[:1])[0]

    def test_three_classes_entropy(self):
        rng = np.random.default_rng(2)
        X = np.vstack([rng.normal(loc=(c * 4, 0), scale=0.6, size=(60, 2))
                       for c in range(3)])
        y = np.repeat(np.arange(3), 60)
        m = train_classifier(X, y, num_classes=3, num_trees=15,
                             impurity="entropy", max_depth=4, seed=3)
        assert (m.predict_batch(X) == y).mean() > 0.95

    def test_deterministic_given_seed(self):
        X, y = blobs(120, seed=4)
        a = train_classifier(X, y, num_classes=2, num_trees=5, seed=9)
        b = train_classifier(X, y, num_classes=2, num_trees=5, seed=9)
        probe = np.random.default_rng(0).normal(size=(50, 3)) * 3
        assert (a.predict_batch(probe) == b.predict_batch(probe)).all()

    def test_max_depth_bounds_tree(self):
        X, y = blobs(200, seed=5)
        m = train_classifier(X, y, num_classes=2, num_trees=3,
                             max_depth=2, seed=1)
        # depth 2 -> at most 7 nodes per tree
        assert all(len(t.feature) <= 7 for t in m.trees)

    def test_pure_node_stops(self):
        X = np.asarray([[0.0, 1.0]] * 10)
        y = np.zeros(10, dtype=np.int64)  # single class: root is a leaf
        m = train_classifier(X, y, num_classes=2, num_trees=2, seed=0)
        assert all(len(t.feature) == 1 for t in m.trees)
        assert m.predict([0.0, 1.0]) == 0.0

    def test_validation_errors(self):
        X, y = blobs(40)
        with pytest.raises(ValueError, match="labels"):
            train_classifier(X, y + 5, num_classes=2)
        with pytest.raises(ValueError, match="impurity"):
            train_classifier(X, y, num_classes=2, impurity="variance")
        with pytest.raises(ValueError, match="zero samples"):
            train_classifier(np.empty((0, 3)), np.empty(0, dtype=int),
                             num_classes=2)

    def test_feature_subset_strategies(self):
        from predictionio_tpu.e2.forest import _n_sub_features

        assert _n_sub_features("auto", 9) == 3
        assert _n_sub_features("sqrt", 9) == 3
        assert _n_sub_features("log2", 8) == 3
        assert _n_sub_features("onethird", 9) == 3
        assert _n_sub_features("all", 9) == 9

    def test_max_depth_validated(self):
        X, y = blobs(40)
        with pytest.raises(ValueError, match="max_depth"):
            train_classifier(X, y, num_classes=2, max_depth=100)
        with pytest.raises(ValueError, match="num_trees"):
            train_classifier(X, y, num_classes=2, num_trees=0)
        with pytest.raises(ValueError, match="max_bins"):
            train_classifier(X, y, num_classes=2, max_bins=0)
        with pytest.raises(ValueError, match="feature_subset_strategy"):
            train_classifier(X, y, num_classes=2,
                             feature_subset_strategy="sqr")

    def test_non_integer_labels_refused_by_template(self, mem_storage):
        from predictionio_tpu.controller import ComputeContext
        from predictionio_tpu.templates.classification import (
            RandomForestParams,
        )
        from predictionio_tpu.templates.classification.engine import (
            LabeledPoint, RandomForestAlgorithm, TrainingData,
        )

        algo = RandomForestAlgorithm(RandomForestParams(num_classes=2))
        td = TrainingData([LabeledPoint(label=1.5, features=(1.0, 2.0)),
                           LabeledPoint(label=0.0, features=(0.0, 1.0))])
        with pytest.raises(ValueError, match="non-integer labels"):
            algo.train(ComputeContext(), td)


class TestRandomForestTemplateAlgorithm:
    def test_trains_and_serves_in_ensemble(self, mem_storage):
        import datetime as dt

        from predictionio_tpu.controller import (
            ComputeContext, EngineParams,
        )
        from predictionio_tpu.data import storage
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage.base import App
        from predictionio_tpu.templates.classification import (
            DataSourceParams, NaiveBayesParams, Query,
            RandomForestParams, engine_factory,
        )

        aid = storage.get_metadata_apps().insert(App(0, "clsapp"))
        le = storage.get_levents()
        le.init(aid)
        rng = np.random.default_rng(0)
        t0 = dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc)
        events = []
        for i in range(40):
            label = i % 2
            base = [1.0, 3.0, 1.0]
            base[0 if label == 0 else 2] += 10.0 + rng.random()
            events.append(Event(
                event="$set", entity_type="user", entity_id=f"u{i}",
                properties={"plan": float(label), "attr0": base[0],
                            "attr1": base[1], "attr2": base[2]},
                event_time=t0))
        le.insert_batch(events, aid)

        engine = engine_factory()
        params = EngineParams(
            data_source_params=("", DataSourceParams(app_name="clsapp")),
            algorithm_params_list=[
                ("naive", NaiveBayesParams()),
                ("randomforest", RandomForestParams(
                    num_classes=2, num_trees=8, max_depth=4, seed=1))])
        ctx = ComputeContext()
        models = engine.train(ctx, params)
        assert len(models) == 2
        assert isinstance(models[1], RandomForestModel)
        rf = engine._algorithms(params)[1]
        assert rf.predict(models[1],
                          Query(features=(12.0, 3.0, 1.0))).label == 0.0
        assert rf.predict(models[1],
                          Query(features=(1.0, 3.0, 12.0))).label == 1.0
        # batch agrees with single
        queries = [(i, Query(features=(float(f), 3.0, 5.0)))
                   for i, f in enumerate((0.5, 12.0, 2.0))]
        batch = dict(rf.batch_predict(ctx, models[1], queries))
        for qx, q in queries:
            assert batch[qx] == rf.predict(models[1], q)

    def test_variant_json_binding(self, mem_storage):
        """camelCase engine.json params bind to RandomForestParams."""
        from predictionio_tpu.templates.classification import (
            engine_factory,
        )

        engine = engine_factory()
        ep = engine.engine_params_from_variant({
            "datasource": {"params": {"appName": "clsapp"}},
            "algorithms": [{"name": "randomforest", "params": {
                "numClasses": 2, "numTrees": 4,
                "featureSubsetStrategy": "all", "impurity": "entropy",
                "maxDepth": 3, "maxBins": 16}}],
        })
        (_, p) = ep.algorithm_params_list[0]
        assert p.num_trees == 4 and p.impurity == "entropy"
        assert p.feature_subset_strategy == "all" and p.max_bins == 16