"""Query-server tests: deploy a trained ALS instance and answer queries
over HTTP (CreateServer.scala behavior: query path, feedback loop, reload,
undeploy-before-bind)."""

import datetime as dt
import json
import time
import urllib.parse

import numpy as np
import pytest

import http.client

from predictionio_tpu.controller import ComputeContext, EngineParams
from predictionio_tpu.data import storage
from predictionio_tpu.data.api import EventServer, EventServerConfig
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import AccessKey, App
from predictionio_tpu.ops.als import ALSParams
from predictionio_tpu.templates.recommendation import (
    DataSourceParams,
    Query,
    engine_factory,
)
from predictionio_tpu.workflow import QueryServer, ServerConfig, run_train
from predictionio_tpu.workflow.create_server import (
    engine_instance_to_engine_params,
    query_from_json,
    to_jsonable,
)
from predictionio_tpu.workflow.create_workflow import (
    WorkflowConfig,
    new_engine_instance,
)

UTC = dt.timezone.utc
CTX = ComputeContext()
FACTORY = "predictionio_tpu.templates.recommendation:engine_factory"


def seed_ratings(app_name="recapp"):
    aid = storage.get_metadata_apps().insert(App(0, app_name))
    le = storage.get_levents()
    le.init(aid)
    rng = np.random.default_rng(0)
    t0 = dt.datetime(2021, 1, 1, tzinfo=UTC)
    events = []
    for u in range(20):
        group = "a" if u < 10 else "b"
        for _ in range(8):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item",
                target_entity_id=f"{group}{rng.integers(0, 10)}",
                properties={"rating": float(rng.integers(4, 6))},
                event_time=t0))
    le.insert_batch(events, aid)
    return aid


def train_once(batch=""):
    engine = engine_factory()
    params = EngineParams(
        data_source_params=("", DataSourceParams(app_name="recapp")),
        algorithm_params_list=[
            ("als", ALSParams(rank=8, num_iterations=3, seed=0))],
    )
    config = WorkflowConfig(engine_factory=FACTORY, batch=batch)
    instance = new_engine_instance(config, params)
    iid = run_train(engine, params, instance, ctx=CTX)
    assert iid is not None
    return iid


@pytest.fixture
def trained(mem_storage):
    seed_ratings()
    return train_once()


def post(addr, path, body, params=None):
    host, port = addr
    conn = http.client.HTTPConnection(host, port, timeout=30)
    target = path + ("?" + urllib.parse.urlencode(params) if params else "")
    conn.request("POST", target, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = json.loads(resp.read().decode("utf-8"))
    conn.close()
    return resp.status, data


def get(addr, path):
    host, port = addr
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = json.loads(resp.read().decode("utf-8"))
    conn.close()
    return resp.status, data


@pytest.fixture
def server(trained):
    srv = QueryServer(ServerConfig(ip="127.0.0.1", port=0)).start(
        undeploy_stale=False)
    yield srv
    srv.stop()


class TestQueryPath:
    def test_queries_json(self, server):
        status, result = post(server.address, "/queries.json",
                              {"user": "u1", "num": 3})
        assert status == 200
        assert len(result["itemScores"]) == 3
        top = result["itemScores"][0]
        assert top["item"].startswith("a") and top["score"] > 0

    def test_unknown_user_empty(self, server):
        status, result = post(server.address, "/queries.json",
                              {"user": "nobody"})
        assert status == 200 and result["itemScores"] == []

    def test_bad_query_400(self, server):
        status, result = post(server.address, "/queries.json",
                              {"bogusField": 1})
        assert status == 400
        status, _ = post(server.address, "/queries.json", "notadict")
        assert status == 400

    def test_status_page_bookkeeping(self, server):
        post(server.address, "/queries.json", {"user": "u1"})
        post(server.address, "/queries.json", {"user": "u2"})
        status, page = get(server.address, "/")
        assert status == 200
        assert page["status"] == "alive"
        assert page["requestCount"] == 2
        assert page["avgServingSec"] > 0
        assert page["algorithms"] == ["ALSAlgorithm"]

    def test_plugins_json(self, server):
        status, page = get(server.address, "/plugins.json")
        assert status == 200
        assert set(page["plugins"]) == {"outputblockers", "outputsniffers"}


class TestReload:
    def test_reload_hot_swaps_latest(self, server):
        _, before = get(server.address, "/")
        iid2 = train_once()
        status, data = post(server.address, "/reload", {})
        assert status == 200 and data["engineInstanceId"] == iid2
        _, after = get(server.address, "/")
        assert after["engineInstanceId"] == iid2 != before["engineInstanceId"]
        # still serves
        status, result = post(server.address, "/queries.json", {"user": "u1"})
        assert status == 200 and result["itemScores"]


class TestFeedbackLoop:
    def test_predict_event_posted(self, trained, mem_storage):
        aid = storage.get_metadata_apps().get_by_name("recapp").id
        storage.get_metadata_access_keys().insert(
            AccessKey(key="fbkey", appid=aid))
        es = EventServer(EventServerConfig(ip="127.0.0.1", port=0),
                         reg=mem_storage).start()
        qs = QueryServer(ServerConfig(
            ip="127.0.0.1", port=0, feedback=True,
            event_server_ip=es.address[0],
            event_server_port=es.address[1],
            access_key="fbkey")).start(undeploy_stale=False)
        try:
            status, result = post(qs.address, "/queries.json", {"user": "u1"})
            assert status == 200
            deadline = time.time() + 10
            fb = []
            while time.time() < deadline and not fb:
                fb = list(storage.get_levents().find(
                    app_id=aid, entity_type="pio_pr"))
                time.sleep(0.05)
            assert fb, "feedback predict event never arrived"
            ev = fb[0]
            assert ev.event == "predict"
            props = ev.properties
            assert props["query"] == {"user": "u1", "items": [],
                                      "num": 10, "blacklist": [],
                                      "categories": []}
            assert props["prediction"]["itemScores"]
            assert props["engineInstanceId"]
        finally:
            qs.stop()
            es.stop()


class TestUndeploy:
    def test_stale_server_undeployed_before_bind(self, trained):
        first = QueryServer(ServerConfig(ip="127.0.0.1", port=0)).start(
            undeploy_stale=False)
        port = first.address[1]
        second = QueryServer(ServerConfig(ip="127.0.0.1", port=port)).start()
        try:
            status, result = post(second.address, "/queries.json",
                                  {"user": "u1"})
            assert status == 200 and result["itemScores"]
        finally:
            second.stop()
            first.stop()


class TestEnsembleQueryClassValidation:
    """Deploy refuses an ensemble whose algorithms disagree on the query
    type (the server types query extraction by the FIRST algorithm,
    CreateServer.scala:519-525 — a mismatch would mis-parse silently)."""

    def test_mismatched_query_classes_refused(self, mem_storage):
        import dataclasses as dc

        from predictionio_tpu.controller import Engine
        from tests.dase_fixtures import (
            DataSource0, IdParams, P2LAlgo0, Preparator0, Serving0,
        )

        @dc.dataclass(frozen=True)
        class OtherQuery:
            text: str = ""

        class AlgoA(P2LAlgo0):
            query_cls = Query  # the template Query

        class AlgoB(P2LAlgo0):
            query_cls = OtherQuery

        engine = Engine(DataSource0, Preparator0,
                        {"a": AlgoA, "b": AlgoB}, Serving0)
        params = EngineParams(
            data_source_params=("", IdParams(1)),
            preparator_params=("", IdParams(1)),
            algorithm_params_list=[("a", IdParams(2)), ("b", IdParams(3))],
            serving_params=("", IdParams(1)),
        )
        cfg = WorkflowConfig(engine_factory="tests:na")
        iid = run_train(engine, params, new_engine_instance(cfg, params),
                        ctx=CTX)
        with pytest.raises(ValueError, match="different query classes"):
            QueryServer(ServerConfig(ip="127.0.0.1", port=0,
                                     engine_instance_id=iid),
                        engine=engine).deploy()

    def test_untyped_first_algorithm_with_typed_member_refused(
            self, mem_storage):
        from predictionio_tpu.controller import Engine
        from tests.dase_fixtures import (
            DataSource0, IdParams, P2LAlgo0, Preparator0, Serving0,
        )

        class AlgoUntyped(P2LAlgo0):
            pass  # no query_cls: extraction would hand raw dicts around

        class AlgoTyped(P2LAlgo0):
            query_cls = Query

        engine = Engine(DataSource0, Preparator0,
                        {"a": AlgoUntyped, "b": AlgoTyped}, Serving0)
        params = EngineParams(
            data_source_params=("", IdParams(1)),
            preparator_params=("", IdParams(1)),
            algorithm_params_list=[("a", IdParams(2)), ("b", IdParams(3))],
            serving_params=("", IdParams(1)),
        )
        cfg = WorkflowConfig(engine_factory="tests:na")
        iid = run_train(engine, params, new_engine_instance(cfg, params),
                        ctx=CTX)
        with pytest.raises(ValueError, match="declares no query class"):
            QueryServer(ServerConfig(ip="127.0.0.1", port=0,
                                     engine_instance_id=iid),
                        engine=engine).deploy()

    def test_shared_query_class_deploys(self, mem_storage):
        from predictionio_tpu.controller import Engine
        from tests.dase_fixtures import (
            DataSource0, IdParams, P2LAlgo0, Preparator0, Serving0,
        )

        class AlgoA(P2LAlgo0):
            query_cls = Query

        class AlgoB(P2LAlgo0):
            query_cls = Query

        engine = Engine(DataSource0, Preparator0,
                        {"a": AlgoA, "b": AlgoB}, Serving0)
        params = EngineParams(
            data_source_params=("", IdParams(1)),
            preparator_params=("", IdParams(1)),
            algorithm_params_list=[("a", IdParams(2)), ("b", IdParams(3))],
            serving_params=("", IdParams(1)),
        )
        cfg = WorkflowConfig(engine_factory="tests:na")
        iid = run_train(engine, params, new_engine_instance(cfg, params),
                        ctx=CTX)
        srv = QueryServer(ServerConfig(ip="127.0.0.1", port=0,
                                       engine_instance_id=iid),
                          engine=engine)
        assert srv.deploy() is srv


class TestHTTPS:
    """TLS serving parity (the reference deploys HTTPS-only,
    CreateServer.scala:332-339 via SSLConfiguration.scala:50-72)."""

    @pytest.fixture
    def cert(self, tmp_path):
        import subprocess

        cert, key = tmp_path / "cert.pem", tmp_path / "key.pem"
        try:
            subprocess.run(
                ["openssl", "req", "-x509", "-newkey", "rsa:2048",
                 "-nodes", "-keyout", str(key), "-out", str(cert),
                 "-days", "1", "-subj", "/CN=localhost"],
                check=True, capture_output=True, timeout=60)
        except (OSError, subprocess.SubprocessError):
            pytest.skip("openssl unavailable")
        server_json = tmp_path / "server.json"
        server_json.write_text(json.dumps(
            {"ssl": {"certfile": str(cert), "keyfile": str(key)}}))
        return str(server_json), str(cert)

    def test_queries_json_over_tls(self, trained, cert):
        import ssl

        server_json, certfile = cert
        srv = QueryServer(ServerConfig(
            ip="127.0.0.1", port=0,
            server_config_path=server_json)).start(undeploy_stale=False)
        try:
            assert srv.scheme == "https"
            host, port = srv.address
            ctx = ssl.create_default_context(cafile=certfile)
            ctx.check_hostname = False  # self-signed, CN only
            conn = http.client.HTTPSConnection(host, port, timeout=60,
                                               context=ctx)
            conn.request("POST", "/queries.json",
                         body=json.dumps({"user": "u1", "num": 3}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = json.loads(resp.read().decode())
            conn.close()
            assert resp.status == 200
            assert 0 < len(data["itemScores"]) <= 3
        finally:
            srv.stop()

    def test_https_undeploy_stale(self, trained, cert):
        from predictionio_tpu.workflow.create_server import undeploy

        server_json, _ = cert
        srv = QueryServer(ServerConfig(
            ip="127.0.0.1", port=0,
            server_config_path=server_json)).start(undeploy_stale=False)
        host, port = srv.address
        try:
            assert undeploy(host, port, scheme="https") is True
            for _ in range(50):
                if srv._httpd is None:
                    break
                time.sleep(0.1)
            assert srv._httpd is None  # /stop shut it down
        finally:
            srv.stop()

    def test_silent_client_does_not_block_other_connections(self, trained,
                                                            cert):
        """A TCP client that never speaks TLS must not pin the accept
        loop (handshake runs in the worker thread with a timeout)."""
        import socket
        import ssl

        server_json, certfile = cert
        srv = QueryServer(ServerConfig(
            ip="127.0.0.1", port=0,
            server_config_path=server_json)).start(undeploy_stale=False)
        try:
            host, port = srv.address
            silent = socket.create_connection((host, port))  # no bytes
            try:
                ctx = ssl.create_default_context(cafile=certfile)
                ctx.check_hostname = False
                conn = http.client.HTTPSConnection(host, port, timeout=15,
                                                   context=ctx)
                conn.request("POST", "/queries.json",
                             body=json.dumps({"user": "u1", "num": 2}),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200
                resp.read()
                conn.close()
            finally:
                silent.close()
        finally:
            srv.stop()

    def test_scheme_switch_still_undeploys_stale(self, trained, cert):
        """An HTTP stale server on the port is replaced by an HTTPS
        deploy (the probe tries both schemes)."""
        server_json, _ = cert
        plain = QueryServer(ServerConfig(ip="127.0.0.1", port=0)).start(
            undeploy_stale=False)
        port = plain.address[1]
        tls = QueryServer(ServerConfig(
            ip="127.0.0.1", port=port,
            server_config_path=server_json)).start()
        try:
            assert tls.scheme == "https" and tls.address[1] == port
        finally:
            tls.stop()
            plain.stop()

    def test_no_ssl_config_stays_http(self, trained, tmp_path):
        server_json = tmp_path / "server.json"
        server_json.write_text(json.dumps({"accessKey": ""}))
        srv = QueryServer(ServerConfig(
            ip="127.0.0.1", port=0,
            server_config_path=str(server_json))).start(
                undeploy_stale=False)
        try:
            assert srv.scheme == "http"
        finally:
            srv.stop()


class TestHelpers:
    def test_engine_instance_to_engine_params(self, trained):
        instance = storage.get_metadata_engine_instances().get(trained)
        engine = engine_factory()
        ep = engine_instance_to_engine_params(engine, instance)
        assert ep.data_source_params[1].app_name == "recapp"
        name, algo_params = ep.algorithm_params_list[0]
        assert name == "als"
        assert (algo_params.rank, algo_params.num_iterations) == (8, 3)

    def test_query_from_json_camel_case(self):
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class Q:
            user_id: str
            num: int = 5
            black_list: tuple = ()

        q = query_from_json(
            {"userId": "u9", "blackList": ["x"]}, Q)
        assert q == Q(user_id="u9", num=5, black_list=("x",))
        with pytest.raises(Exception):
            query_from_json({"nope": 1}, Q)

    def test_to_jsonable(self):
        q = Query(user="u1", items=("a", "b"))
        assert to_jsonable(q) == {"user": "u1", "items": ["a", "b"],
                                  "num": 10, "blacklist": [],
                                  "categories": []}
        assert to_jsonable(np.float32(1.5)) == 1.5
        assert to_jsonable({"a": np.arange(2)}) == {"a": [0, 1]}
