"""Query-server tests: deploy a trained ALS instance and answer queries
over HTTP (CreateServer.scala behavior: query path, feedback loop, reload,
undeploy-before-bind)."""

import datetime as dt
import json
import time
import urllib.parse

import numpy as np
import pytest

import http.client

from predictionio_tpu.controller import ComputeContext, EngineParams
from predictionio_tpu.data import storage
from predictionio_tpu.data.api import EventServer, EventServerConfig
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import AccessKey, App
from predictionio_tpu.ops.als import ALSParams
from predictionio_tpu.templates.recommendation import (
    DataSourceParams,
    Query,
    engine_factory,
)
from predictionio_tpu.workflow import QueryServer, ServerConfig, run_train
from predictionio_tpu.workflow.create_server import (
    engine_instance_to_engine_params,
    query_from_json,
    to_jsonable,
)
from predictionio_tpu.workflow.create_workflow import (
    WorkflowConfig,
    new_engine_instance,
)

UTC = dt.timezone.utc
CTX = ComputeContext()
FACTORY = "predictionio_tpu.templates.recommendation:engine_factory"


def seed_ratings(app_name="recapp"):
    aid = storage.get_metadata_apps().insert(App(0, app_name))
    le = storage.get_levents()
    le.init(aid)
    rng = np.random.default_rng(0)
    t0 = dt.datetime(2021, 1, 1, tzinfo=UTC)
    events = []
    for u in range(20):
        group = "a" if u < 10 else "b"
        for _ in range(8):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item",
                target_entity_id=f"{group}{rng.integers(0, 10)}",
                properties={"rating": float(rng.integers(4, 6))},
                event_time=t0))
    le.insert_batch(events, aid)
    return aid


def train_once(batch=""):
    engine = engine_factory()
    params = EngineParams(
        data_source_params=("", DataSourceParams(app_name="recapp")),
        algorithm_params_list=[
            ("als", ALSParams(rank=8, num_iterations=3, seed=0))],
    )
    config = WorkflowConfig(engine_factory=FACTORY, batch=batch)
    instance = new_engine_instance(config, params)
    iid = run_train(engine, params, instance, ctx=CTX)
    assert iid is not None
    return iid


@pytest.fixture
def trained(mem_storage):
    seed_ratings()
    return train_once()


def post(addr, path, body, params=None):
    host, port = addr
    conn = http.client.HTTPConnection(host, port, timeout=30)
    target = path + ("?" + urllib.parse.urlencode(params) if params else "")
    conn.request("POST", target, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = json.loads(resp.read().decode("utf-8"))
    conn.close()
    return resp.status, data


def get(addr, path):
    host, port = addr
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = json.loads(resp.read().decode("utf-8"))
    conn.close()
    return resp.status, data


@pytest.fixture
def server(trained):
    srv = QueryServer(ServerConfig(ip="127.0.0.1", port=0)).start(
        undeploy_stale=False)
    yield srv
    srv.stop()


class TestQueryPath:
    def test_queries_json(self, server):
        status, result = post(server.address, "/queries.json",
                              {"user": "u1", "num": 3})
        assert status == 200
        assert len(result["itemScores"]) == 3
        top = result["itemScores"][0]
        assert top["item"].startswith("a") and top["score"] > 0

    def test_unknown_user_empty(self, server):
        status, result = post(server.address, "/queries.json",
                              {"user": "nobody"})
        assert status == 200 and result["itemScores"] == []

    def test_bad_query_400(self, server):
        status, result = post(server.address, "/queries.json",
                              {"bogusField": 1})
        assert status == 400
        status, _ = post(server.address, "/queries.json", "notadict")
        assert status == 400

    def test_status_page_bookkeeping(self, server):
        post(server.address, "/queries.json", {"user": "u1"})
        post(server.address, "/queries.json", {"user": "u2"})
        status, page = get(server.address, "/")
        assert status == 200
        assert page["status"] == "alive"
        assert page["requestCount"] == 2
        assert page["avgServingSec"] > 0
        assert page["algorithms"] == ["ALSAlgorithm"]

    def test_plugins_json(self, server):
        status, page = get(server.address, "/plugins.json")
        assert status == 200
        assert set(page["plugins"]) == {"outputblockers", "outputsniffers"}


class TestReload:
    def test_reload_hot_swaps_latest(self, server):
        _, before = get(server.address, "/")
        iid2 = train_once()
        status, data = post(server.address, "/reload", {})
        assert status == 200 and data["engineInstanceId"] == iid2
        _, after = get(server.address, "/")
        assert after["engineInstanceId"] == iid2 != before["engineInstanceId"]
        # still serves
        status, result = post(server.address, "/queries.json", {"user": "u1"})
        assert status == 200 and result["itemScores"]


class TestFeedbackLoop:
    def test_predict_event_posted(self, trained, mem_storage):
        aid = storage.get_metadata_apps().get_by_name("recapp").id
        storage.get_metadata_access_keys().insert(
            AccessKey(key="fbkey", appid=aid))
        es = EventServer(EventServerConfig(ip="127.0.0.1", port=0),
                         reg=mem_storage).start()
        qs = QueryServer(ServerConfig(
            ip="127.0.0.1", port=0, feedback=True,
            event_server_ip=es.address[0],
            event_server_port=es.address[1],
            access_key="fbkey")).start(undeploy_stale=False)
        try:
            status, result = post(qs.address, "/queries.json", {"user": "u1"})
            assert status == 200
            deadline = time.time() + 10
            fb = []
            while time.time() < deadline and not fb:
                fb = list(storage.get_levents().find(
                    app_id=aid, entity_type="pio_pr"))
                time.sleep(0.05)
            assert fb, "feedback predict event never arrived"
            ev = fb[0]
            assert ev.event == "predict"
            props = ev.properties
            assert props["query"] == {"user": "u1", "items": [],
                                      "num": 10, "blacklist": []}
            assert props["prediction"]["itemScores"]
            assert props["engineInstanceId"]
        finally:
            qs.stop()
            es.stop()


class TestUndeploy:
    def test_stale_server_undeployed_before_bind(self, trained):
        first = QueryServer(ServerConfig(ip="127.0.0.1", port=0)).start(
            undeploy_stale=False)
        port = first.address[1]
        second = QueryServer(ServerConfig(ip="127.0.0.1", port=port)).start()
        try:
            status, result = post(second.address, "/queries.json",
                                  {"user": "u1"})
            assert status == 200 and result["itemScores"]
        finally:
            second.stop()
            first.stop()


class TestHelpers:
    def test_engine_instance_to_engine_params(self, trained):
        instance = storage.get_metadata_engine_instances().get(trained)
        engine = engine_factory()
        ep = engine_instance_to_engine_params(engine, instance)
        assert ep.data_source_params[1].app_name == "recapp"
        name, algo_params = ep.algorithm_params_list[0]
        assert name == "als"
        assert (algo_params.rank, algo_params.num_iterations) == (8, 3)

    def test_query_from_json_camel_case(self):
        import dataclasses

        @dataclasses.dataclass(frozen=True)
        class Q:
            user_id: str
            num: int = 5
            black_list: tuple = ()

        q = query_from_json(
            {"userId": "u9", "blackList": ["x"]}, Q)
        assert q == Q(user_id="u9", num=5, black_list=("x",))
        with pytest.raises(Exception):
            query_from_json({"nope": 1}, Q)

    def test_to_jsonable(self):
        q = Query(user="u1", items=("a", "b"))
        assert to_jsonable(q) == {"user": "u1", "items": ["a", "b"],
                                  "num": 10, "blacklist": []}
        assert to_jsonable(np.float32(1.5)) == 1.5
        assert to_jsonable({"a": np.arange(2)}) == {"a": [0, 1]}
