"""sequentialrec template tests: datasource (single-scan == streamed),
time-ordering preparator, train -> next-item predict, shared eval
protocols, deployed serving with the zero-compile gate, and online
fold-in freshness (a user's NEW event changes their served top-k with
no retrain and no /reload)."""

import datetime as dt
import http.client
import json
import time

import numpy as np
import pytest

from predictionio_tpu.controller import ComputeContext, EngineParams
from predictionio_tpu.data import storage
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.templates.sequentialrec import (
    DataSourceParams,
    Query,
    SeqPreparatorParams,
    SeqRecParams,
    SequenceDataSource,
    SequencePreparator,
    engine_factory,
)

UTC = dt.timezone.utc
CTX = ComputeContext()
T0 = dt.datetime(2024, 1, 1, tzinfo=UTC)
FACTORY = "predictionio_tpu.templates.sequentialrec:engine_factory"
N_ITEMS = 40


def view_event(user, item, minutes=0.0):
    return Event(event="view", entity_type="user", entity_id=user,
                 target_entity_type="item", target_entity_id=item,
                 event_time=T0 + dt.timedelta(minutes=minutes))


def seed_chains(app_name="seqapp", n_users=50, n_items=N_ITEMS, seed=0):
    """Deterministic chain stream: each user walks item (start+j) % M —
    the next item after a user's last is always predictable."""
    aid = storage.get_metadata_apps().insert(App(0, app_name))
    le = storage.get_levents()
    le.init(aid)
    rng = np.random.default_rng(seed)
    events = []
    for u in range(n_users):
        start = int(rng.integers(0, n_items))
        n = int(rng.integers(4, 12))
        for j in range(n):
            events.append(view_event(
                f"u{u}", f"i{(start + j) % n_items}", minutes=j))
    le.insert_batch(events, aid)
    return aid


def algo_params(num_steps=150, seed=0, **kw):
    return SeqRecParams(rank=16, n_layers=2, n_heads=2, max_seq_len=16,
                        num_steps=num_steps, batch_size=32,
                        n_negatives=32, learning_rate=0.01, seed=seed,
                        **kw)


def make_params(app_name="seqapp", **kw):
    return EngineParams(
        data_source_params=("", DataSourceParams(app_name=app_name)),
        preparator_params=("", SeqPreparatorParams(max_seq_len=16)),
        algorithm_params_list=[("seqrec", algo_params(**kw))],
    )


def train_instance(app_name="seqapp", **kw):
    from predictionio_tpu.workflow import run_train
    from predictionio_tpu.workflow.create_workflow import (
        WorkflowConfig,
        new_engine_instance,
    )

    engine = engine_factory()
    params = make_params(app_name, **kw)
    config = WorkflowConfig(engine_factory=FACTORY)
    iid = run_train(engine, params, new_engine_instance(config, params),
                    ctx=CTX)
    assert iid is not None
    return iid


def _post(addr, path, body):
    host, port = addr
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = json.loads(resp.read().decode("utf-8"))
    conn.close()
    return resp.status, data


class TestDataSource:
    def test_streamed_read_matches_single_scan(self, mem_storage):
        seed_chains()
        single = SequenceDataSource(DataSourceParams(
            app_name="seqapp")).read_training(CTX)
        streamed = SequenceDataSource(DataSourceParams(
            app_name="seqapp", streaming_block_size=37,
            decode_prefetch=2)).read_training(CTX)
        assert len(single) == len(streamed)
        # same multiset of (user, item, time) triples whatever the
        # block boundaries were
        def canon(td):
            return sorted(zip(td.users.astype(str),
                              td.items.astype(str), td.times))
        assert canon(single) == canon(streamed)

    def test_targetless_events_filtered(self, mem_storage):
        aid = storage.get_metadata_apps().insert(App(0, "seqapp"))
        le = storage.get_levents()
        le.init(aid)
        le.insert_batch([
            view_event("u1", "i1", 0),
            Event(event="view", entity_type="user", entity_id="u1",
                  event_time=T0),  # no target
        ], aid)
        td = SequenceDataSource(DataSourceParams(
            app_name="seqapp")).read_training(CTX)
        assert len(td) == 1

    def test_leave_last_out_eval_holds_most_recent(self, mem_storage):
        aid = storage.get_metadata_apps().insert(App(0, "seqapp"))
        le = storage.get_levents()
        le.init(aid)
        # u1's events arrive OUT of time order: the held-out actual
        # must be the latest by TIME (i9), not by arrival
        le.insert_batch([
            view_event("u1", "i9", minutes=50),
            view_event("u1", "i1", minutes=1),
            view_event("u1", "i2", minutes=2),
            view_event("u2", "i3", minutes=1),
        ], aid)
        sets = SequenceDataSource(DataSourceParams(
            app_name="seqapp")).read_eval(CTX)
        assert len(sets) == 1
        td, _, qa = sets[0]
        held = {q.user: a.items for q, a in qa}
        assert held == {"u1": ("i9",)}
        assert len(td) == 3  # u2's single event trains whole

    def test_sliding_eval_windows(self, mem_storage):
        aid = storage.get_metadata_apps().insert(App(0, "seqapp"))
        le = storage.get_levents()
        le.init(aid)
        le.insert_batch(
            [view_event("u1", f"i{j}", minutes=j * 1440) # one per day
             for j in range(10)], aid)
        ds = SequenceDataSource(DataSourceParams(
            app_name="seqapp",
            eval_first_until=(T0 + dt.timedelta(days=5)).isoformat(),
            eval_duration_days=2.0, eval_count=2))
        sets = ds.read_eval(CTX)
        assert len(sets) == 2
        td0, _, qa0 = sets[0]
        assert len(td0) == 5                      # days 0..4
        assert qa0[0][1].items == ("i5", "i6")    # days 5, 6
        td1, _, qa1 = sets[1]
        assert len(td1) == 7
        assert qa1[0][1].items == ("i7", "i8")


class TestPreparator:
    def test_sequences_are_time_ordered(self, mem_storage):
        aid = storage.get_metadata_apps().insert(App(0, "seqapp"))
        le = storage.get_levents()
        le.init(aid)
        le.insert_batch([
            view_event("u1", "i3", minutes=30),
            view_event("u1", "i1", minutes=10),
            view_event("u1", "i2", minutes=20),
        ], aid)
        td = SequenceDataSource(DataSourceParams(
            app_name="seqapp")).read_training(CTX)
        pd = SequencePreparator(SeqPreparatorParams(
            max_seq_len=16)).prepare(CTX, td)
        (bucket,) = pd.buckets
        decoded = pd.item_map.decode(
            bucket.ids[0][:3].astype(np.int64))
        assert list(decoded) == ["i1", "i2", "i3"]

    def test_seen_sets_cover_history(self, mem_storage):
        seed_chains(n_users=5)
        td = SequenceDataSource(DataSourceParams(
            app_name="seqapp")).read_training(CTX)
        pd = SequencePreparator(SeqPreparatorParams(
            max_seq_len=16)).prepare(CTX, td)
        for u, items in pd.seen.items():
            assert len(items) == len(np.unique(items))
            assert len(items) >= 1


class TestTrainPredict:
    def test_next_item_predicted_on_chain(self, mem_storage):
        seed_chains(seed=3)
        engine = engine_factory()
        params = make_params(seed=3)
        model = engine.train(CTX, params)[0]
        algo = engine._algorithms(params)[0]
        # for most users the top prediction should be the chain's next
        # item (their own history is seen-masked away)
        le = storage.get_levents()
        aid = storage.get_metadata_apps().get_by_name("seqapp").id
        hits = total = 0
        for u in range(0, 30, 3):
            evs = sorted(le.find(aid, entity_id=f"u{u}"),
                         key=lambda e: e.event_time)
            if not evs:
                continue
            nxt = f"i{(int(evs[-1].target_entity_id[1:]) + 1) % N_ITEMS}"
            r = algo.predict(model, Query(user=f"u{u}", num=10))
            total += 1
            hits += nxt in {s.item for s in r.item_scores}
        assert total >= 8
        assert hits / total > 0.7

    def test_all_negative_scores_still_serve_a_ranking(self,
                                                       mem_storage):
        """Transformer logits are only relatively calibrated: a user
        whose dot products are ALL negative must still get their num
        results (serve_positive_scores_only=False opts out of the
        implicit-ALS positivity filter), while device masks (-inf seen
        items) still drop."""
        from predictionio_tpu.data.bimap import StringIndexBiMap
        from predictionio_tpu.ops.seqrec import SeqRecParams, init_theta
        from predictionio_tpu.templates.sequentialrec import (
            SeqRecAlgorithm,
            SeqRecModel,
        )

        params = algo_params()
        theta = init_theta(6, params)
        model = SeqRecModel(
            user_vectors=-np.ones((2, 16), dtype=np.float32),
            item_vectors=np.ones((6, 16), dtype=np.float32),
            user_map=StringIndexBiMap.from_distinct(
                np.asarray(["u0", "u1"], dtype=object)),
            item_map=StringIndexBiMap.from_distinct(
                np.asarray([f"i{j}" for j in range(6)], dtype=object)),
            seen={0: np.asarray([0, 1])},
            theta=theta, enc_params=params, max_seq_len=16)
        algo = SeqRecAlgorithm(params)
        r = algo.predict(model, Query(user="u0", num=3))
        assert len(r.item_scores) == 3
        assert all(s.score < 0 for s in r.item_scores)
        assert {s.item for s in r.item_scores}.isdisjoint({"i0", "i1"})

    def test_unknown_user_empty(self, mem_storage):
        seed_chains(n_users=10)
        engine = engine_factory()
        params = make_params(num_steps=20)
        model = engine.train(CTX, params)[0]
        algo = engine._algorithms(params)[0]
        assert algo.predict(model, Query(user="nobody")).item_scores == ()

    def test_batch_predict_matches_single(self, mem_storage):
        seed_chains(n_users=12)
        engine = engine_factory()
        params = make_params(num_steps=30)
        model = engine.train(CTX, params)[0]
        algo = engine._algorithms(params)[0]
        qs = [(i, Query(user=f"u{i}", num=5)) for i in range(8)]
        batch = dict(algo.batch_predict(CTX, model, qs))
        for qx, q in qs:
            assert batch[qx] == algo.predict(model, q)

    def test_model_pickles_and_serves_after_reload(self, mem_storage):
        import pickle

        seed_chains(n_users=10)
        engine = engine_factory()
        params = make_params(num_steps=30)
        model = engine.train(CTX, params)[0]
        algo = engine._algorithms(params)[0]
        want = algo.predict(model, Query(user="u1", num=5))
        # a fold populates the cached device theta; pickling must drop
        # it along with the serving handles
        model.fold_in_rows([np.asarray([0, 1], dtype=np.int64)],
                           [np.ones(2, np.float32)])
        assert getattr(model, "_theta_device", None) is not None
        clone = pickle.loads(pickle.dumps(model))
        assert clone._server is None  # device handles dropped
        assert getattr(clone, "_theta_device", None) is None
        got = algo.predict(clone, Query(user="u1", num=5))
        assert got == want

    def test_fold_in_rows_matches_training_encode(self, mem_storage):
        """The fold-in hook re-encodes a user's own (time-ordered)
        history into their trained user vector: EXACT vs the
        single-device encoder, and within the sequence-parallel
        reduction-order tolerance vs the model's stored vectors (the
        test mesh makes training encode through ring/Ulysses)."""
        from predictionio_tpu.ops.seqrec import (
            bucket_sequences,
            encode_users,
        )

        seed_chains(n_users=10, seed=5)
        engine = engine_factory()
        params = make_params(num_steps=30, seed=5)
        model = engine.train(CTX, params)[0]
        le = storage.get_levents()
        aid = storage.get_metadata_apps().get_by_name("seqapp").id
        for user in ("u0", "u3"):
            evs = sorted(le.find(aid, entity_id=user),
                         key=lambda e: e.event_time)
            cols = np.asarray(
                [model.item_map[e.target_entity_id] for e in evs],
                dtype=np.int64)
            rows = model.fold_in_rows([cols], [np.ones(len(cols),
                                                       np.float32)])
            uidx = model.user_map[user]
            # exact vs the single-device encode of the same sequence
            ref = encode_users(
                model.theta, bucket_sequences([cols], max_len=16), 1,
                model.enc_params)
            np.testing.assert_array_equal(rows[0], ref[0])
            # within SP tolerance vs the (mesh-encoded) stored vector
            np.testing.assert_allclose(rows[0],
                                       model.user_vectors[uidx],
                                       rtol=2e-4, atol=1e-5)


class TestDeployedServing:
    def test_deploy_query_and_zero_compile_gate(self, mem_storage,
                                                monkeypatch):
        """Deployed sequentialrec answers top-k through DeviceTopK with
        the steady-state zero-compile gate GREEN (jit-monitor asserted,
        not eyeballed) — the template inherits the AOT bucket ladder."""
        from predictionio_tpu.utils import metrics
        from predictionio_tpu.workflow import QueryServer, ServerConfig

        monkeypatch.setenv("PIO_SERVING_BACKEND", "device")
        seed_chains(seed=1)
        train_instance(seed=1)
        assert metrics.install_jit_compile_listener()
        srv = QueryServer(ServerConfig(ip="127.0.0.1", port=0)).start(
            undeploy_stale=False)
        try:
            # warm request outside the gate (lazy HTTP-layer caches)
            status, result = _post(srv.address, "/queries.json",
                                   {"user": "u1", "num": 3})
            assert status == 200 and len(result["itemScores"]) == 3
            c0 = metrics.JIT_COMPILES.value()
            for u in range(2, 20):
                status, result = _post(srv.address, "/queries.json",
                                       {"user": f"u{u}",
                                        "num": 3 + (u % 8)})
                assert status == 200
                assert result["itemScores"]
            assert metrics.JIT_COMPILES.value() - c0 == 0, \
                "a steady-state sequentialrec query paid an XLA compile"
        finally:
            srv.stop()

    @pytest.mark.online
    def test_foldin_freshness_new_event_changes_topk(self, mem_storage,
                                                     monkeypatch):
        """The acceptance gate: a user's NEW event changes their served
        top-k within the default cadence — no retrain, no /reload. On
        the chain stream the change is DETERMINISTIC: after watching
        items a..b the model recommends b+1; one new view of item x
        moves the recommendation to x+1."""
        from predictionio_tpu.workflow import QueryServer, ServerConfig

        monkeypatch.setenv("PIO_FOLDIN_INTERVAL", "0.2")
        aid = seed_chains(seed=7)
        train_instance(seed=7, num_steps=200)
        srv = QueryServer(ServerConfig(ip="127.0.0.1", port=0,
                                       foldin=True)).start(
            undeploy_stale=False)
        try:
            status, before = _post(srv.address, "/queries.json",
                                   {"user": "u2", "num": 5})
            assert status == 200 and before["itemScores"]
            # a fresh walk segment far from u2's history: the re-encode
            # must steer the top-k toward the new segment's successor
            le = storage.get_levents()
            before_top = [s["item"] for s in before["itemScores"]]
            new_items = [f"i{(int(before_top[0][1:]) + 15 + j) % N_ITEMS}"
                         for j in range(3)]
            for j, it in enumerate(new_items):
                le.insert(view_event("u2", it, minutes=10_000 + j), aid)
            expect = f"i{(int(new_items[-1][1:]) + 1) % N_ITEMS}"
            deadline = time.time() + 15
            changed = None
            while time.time() < deadline:
                status, after = _post(srv.address, "/queries.json",
                                      {"user": "u2", "num": 5})
                assert status == 200
                top = [s["item"] for s in after["itemScores"]]
                if top and top != before_top:
                    changed = top
                    break
                time.sleep(0.05)
            assert changed is not None, \
                "new event never changed the served top-k (no fold?)"
            assert expect in changed, (
                f"fold-in re-encode should recommend the new segment's "
                f"successor {expect}, got {changed}")
            # the new events are seen-masked out of the served list
            assert set(changed).isdisjoint(set(new_items))
        finally:
            srv.stop()

    @pytest.mark.online
    def test_new_user_servable_without_reload(self, mem_storage,
                                              monkeypatch):
        from predictionio_tpu.workflow import QueryServer, ServerConfig

        monkeypatch.setenv("PIO_FOLDIN_INTERVAL", "0.2")
        aid = seed_chains(seed=9)
        train_instance(seed=9)
        srv = QueryServer(ServerConfig(ip="127.0.0.1", port=0,
                                       foldin=True)).start(
            undeploy_stale=False)
        try:
            status, result = _post(srv.address, "/queries.json",
                                   {"user": "fresh1"})
            assert status == 200 and result["itemScores"] == []
            le = storage.get_levents()
            for j in range(3):
                le.insert(view_event("fresh1", f"i{10 + j}",
                                     minutes=20_000 + j), aid)
            deadline = time.time() + 15
            result = None
            while time.time() < deadline:
                status, r = _post(srv.address, "/queries.json",
                                  {"user": "fresh1", "num": 5})
                assert status == 200
                if r["itemScores"]:
                    result = r
                    break
                time.sleep(0.05)
            assert result is not None, "fresh user never became servable"
            items = {s["item"] for s in result["itemScores"]}
            assert items.isdisjoint({"i10", "i11", "i12"})
        finally:
            srv.stop()


class TestRegistry:
    def test_template_listed(self, capsys):
        from predictionio_tpu.tools.template_commands import (
            BUILTIN_TEMPLATES,
            template_list,
        )

        assert "sequentialrec" in BUILTIN_TEMPLATES
        t = BUILTIN_TEMPLATES["sequentialrec"]
        assert t["engineFactory"] == FACTORY
        assert template_list() == 0
        out = capsys.readouterr().out
        assert "sequentialrec" in out

    def test_variant_params_resolve(self):
        """The registry variant's camelCase params must round-trip into
        the template's dataclasses (a stale registry entry would fail
        pio train at param-parse time)."""
        from predictionio_tpu.controller.engine import params_from_dict
        from predictionio_tpu.tools.template_commands import (
            BUILTIN_TEMPLATES,
        )

        variant = BUILTIN_TEMPLATES["sequentialrec"]["variant"]
        algo = variant["algorithms"][0]
        p = params_from_dict(SeqRecParams, algo["params"])
        assert p.rank == 32 and p.n_layers == 2 and p.num_steps == 300
        prep = params_from_dict(SeqPreparatorParams,
                                variant["preparator"]["params"])
        assert prep.max_seq_len == 32
