"""The fused gather->score->mask->top-k serving kernel vs the XLA
chain (interpret mode on CPU — semantics identical to TPU execution).

Exact-agreement strategy: the fp32 suites draw INTEGER-valued factors,
so every score is an exact small-integer dot product — bitwise
identical whatever reduction order the two implementations use — and
``assert_array_equal`` on indices AND scores is meaningful. The
continuous-data suites assert allclose + index-set agreement instead
(fp32 reduction order may differ in the last ulp). Slots whose score
is -inf carry no defined index in either implementation and are
excluded, exactly as every caller filters them."""

import numpy as np
import pytest

import jax.numpy as jnp

from predictionio_tpu.ops.als_pallas import fused_gather_score_topk
from predictionio_tpu.ops.quantize import (
    dequantize_rows_np,
    quantize_rows_int8,
)
from predictionio_tpu.ops.serving import DeviceTopK

pytestmark = pytest.mark.pallas


def xla_chain_topk(Q, Y, seen_cols, seen_mask, k, n_items):
    """The reference gather/einsum/mask/top-k chain, per query row."""
    scores = np.asarray(Y, dtype=np.float32) @ \
        np.asarray(Q, dtype=np.float32).T            # [M, B]
    if seen_cols is not None:
        L, B = seen_cols.shape
        for l in range(L):
            for b in range(B):
                if seen_mask[l, b] > 0:
                    scores[seen_cols[l, b], b] = -np.inf
    scores[n_items:, :] = -np.inf
    idx = np.empty((Q.shape[0], k), dtype=np.int64)
    vals = np.empty((Q.shape[0], k), dtype=np.float32)
    for b in range(Q.shape[0]):
        order = np.argsort(-scores[:, b], kind="stable")[:k]
        idx[b] = order
        vals[b] = scores[order, b]
    return vals, idx


def int_factors(rng, shape, lo=-6, hi=7):
    return rng.integers(lo, hi, shape).astype(np.float32)


class TestKernelExactAgreement:
    @pytest.mark.parametrize("B,M,R,L,k", [
        (1, 17, 4, 1, 5),        # single query, sub-tile catalog
        (5, 33, 6, 4, 7),        # odd everything
        (8, 128, 8, 8, 16),      # exactly one tile
        (3, 300, 8, 6, 16),      # multi-tile with partial pad
    ])
    def test_masked_fp32_exact(self, B, M, R, L, k):
        rng = np.random.default_rng(B * M + k)
        Q = int_factors(rng, (B, R))
        Y = int_factors(rng, (M, R))
        sc = rng.integers(0, M, (L, B)).astype(np.int32)
        sm = (rng.random((L, B)) < 0.7).astype(np.float32)
        n_items = M - 2
        vals, idx = fused_gather_score_topk(
            jnp.asarray(Q), jnp.asarray(Y), sc, sm, k=k,
            n_items=n_items, mask_seen=True, interpret=True)
        wv, wi = xla_chain_topk(Q, Y, sc, sm, k, n_items)
        vals, idx = np.asarray(vals), np.asarray(idx)
        fin = np.isfinite(wv)
        np.testing.assert_array_equal(idx[fin], wi[fin])
        np.testing.assert_array_equal(vals[fin], wv[fin])
        # -inf slots agree on being -inf
        assert (vals[~fin] == -np.inf).all()

    def test_no_mask_exact(self):
        rng = np.random.default_rng(0)
        Q = int_factors(rng, (4, 5))
        Y = int_factors(rng, (40, 5))
        vals, idx = fused_gather_score_topk(
            jnp.asarray(Q), jnp.asarray(Y), None, None, k=6,
            n_items=40, mask_seen=False, interpret=True)
        wv, wi = xla_chain_topk(Q, Y, None, None, 6, 40)
        np.testing.assert_array_equal(np.asarray(idx), wi)
        np.testing.assert_array_equal(np.asarray(vals), wv)

    def test_tie_break_lowest_index_first(self):
        """Duplicate item rows produce tied scores; lax.top_k (and the
        chain) keep the LOWEST item id first — the kernel's running
        heap must reproduce that across tile boundaries."""
        Q = np.asarray([[1.0, 0.0]], dtype=np.float32)
        Y = np.zeros((200, 2), dtype=np.float32)
        Y[:, 0] = 7.0                      # every item ties at score 7
        vals, idx = fused_gather_score_topk(
            jnp.asarray(Q), jnp.asarray(Y), None, None, k=5,
            n_items=200, mask_seen=False, interpret=True)
        np.testing.assert_array_equal(np.asarray(idx)[0],
                                      [0, 1, 2, 3, 4])
        assert (np.asarray(vals)[0] == 7.0).all()

    def test_all_masked_returns_neg_inf(self):
        Q = np.ones((2, 3), dtype=np.float32)
        Y = np.ones((10, 3), dtype=np.float32)
        sc = np.tile(np.arange(10, dtype=np.int32)[:, None], (1, 2))
        sm = np.ones((10, 2), dtype=np.float32)
        vals, _ = fused_gather_score_topk(
            jnp.asarray(Q), jnp.asarray(Y), sc, sm, k=4,
            n_items=10, mask_seen=True, interpret=True)
        assert (np.asarray(vals) == -np.inf).all()

    def test_continuous_data_allclose(self):
        rng = np.random.default_rng(7)
        Q = rng.normal(size=(6, 8)).astype(np.float32)
        Y = rng.normal(size=(150, 8)).astype(np.float32)
        vals, idx = fused_gather_score_topk(
            jnp.asarray(Q), jnp.asarray(Y), None, None, k=10,
            n_items=150, mask_seen=False, interpret=True)
        wv, wi = xla_chain_topk(Q, Y, None, None, 10, 150)
        np.testing.assert_allclose(np.asarray(vals), wv, rtol=1e-5)
        for b in range(6):
            assert set(np.asarray(idx)[b].tolist()) == \
                set(wi[b].tolist())


class TestKernelInt8:
    def test_int8_exact_vs_dequant_chain(self):
        """Int8 tiles dequantize in VMEM; with rows whose absmax is
        exactly 127 the scale is 1.0, dequant is exact, and the kernel
        must match the dequantize-then-chain oracle bitwise."""
        rng = np.random.default_rng(11)
        Y = rng.integers(-127, 128, (70, 6)).astype(np.float32)
        Y[:, 0] = 127.0                     # pin scale == 1.0 per row
        Q = rng.integers(-5, 6, (4, 6)).astype(np.float32)
        Yq = quantize_rows_int8(Y)
        vals, idx = fused_gather_score_topk(
            jnp.asarray(Q), Yq, None, None, k=8, n_items=70,
            mask_seen=False, interpret=True)
        wv, wi = xla_chain_topk(Q, dequantize_rows_np(Yq), None, None,
                                8, 70)
        np.testing.assert_array_equal(np.asarray(idx), wi)
        np.testing.assert_array_equal(np.asarray(vals), wv)

    def test_int8_random_scales_allclose(self):
        rng = np.random.default_rng(12)
        Y = (rng.normal(size=(90, 5)) * 3).astype(np.float32)
        Q = rng.normal(size=(3, 5)).astype(np.float32)
        Yq = quantize_rows_int8(Y)
        vals, _ = fused_gather_score_topk(
            jnp.asarray(Q), Yq, None, None, k=6, n_items=90,
            mask_seen=False, interpret=True)
        wv, _ = xla_chain_topk(Q, dequantize_rows_np(Yq), None, None,
                               6, 90)
        np.testing.assert_allclose(np.asarray(vals), wv, rtol=1e-5)


class TestDeviceTopKFusedEndToEnd:
    """PIO_SERVE_KERNEL=fused routes every DeviceTopK dispatch path
    through the kernel; each must agree with its own XLA-chain twin
    (integer factors -> exact)."""

    @pytest.fixture()
    def factor_pair(self):
        rng = np.random.default_rng(21)
        X = int_factors(rng, (20, 6))
        Y = int_factors(rng, (33, 6))
        seen = {u: rng.choice(33, size=rng.integers(1, 6),
                              replace=False)
                for u in range(0, 20, 2)}
        return X, Y, seen

    def _pair(self, monkeypatch, factor_pair, **kw):
        X, Y, seen = factor_pair
        monkeypatch.setenv("PIO_SERVE_KERNEL", "fused")
        fused = DeviceTopK(X, Y, seen, microbatch=False, **kw)
        assert fused._kernel == "fused"
        monkeypatch.setenv("PIO_SERVE_KERNEL", "xla")
        xla = DeviceTopK(X, Y, seen, microbatch=False, **kw)
        assert xla._kernel == "xla"
        return fused, xla

    def test_user_topk_paths_agree(self, monkeypatch, factor_pair):
        fused, xla = self._pair(monkeypatch, factor_pair)
        for uid in (0, 1, 7, 19):
            fi, fs = fused.user_topk(uid, 5)
            xi, xs = xla.user_topk(uid, 5)
            np.testing.assert_array_equal(fi, xi)
            np.testing.assert_array_equal(fs, xs)

    def test_users_topk_bucket_agrees(self, monkeypatch, factor_pair):
        fused, xla = self._pair(monkeypatch, factor_pair)
        uids = np.asarray([0, 3, 7, 12, 19])
        fi, fs = fused.users_topk(uids, 5)
        xi, xs = xla.users_topk(uids, 5)
        fin = np.isfinite(xs)
        np.testing.assert_array_equal(fi[fin], xi[fin])
        np.testing.assert_array_equal(fs[fin], xs[fin])

    def test_items_topk_agrees(self, monkeypatch, factor_pair):
        """Axis-aligned item rows keep the normalized matrix exact, so
        the similarity lane agrees exactly too."""
        rng = np.random.default_rng(5)
        X = int_factors(rng, (6, 4))
        Y = np.zeros((12, 4), dtype=np.float32)
        for m in range(12):  # +-unit one-hots: unit rows, exact norms
            Y[m, m % 4] = 1.0 if m % 3 else -1.0
        monkeypatch.setenv("PIO_SERVE_KERNEL", "fused")
        fused = DeviceTopK(X, Y, microbatch=False)
        monkeypatch.setenv("PIO_SERVE_KERNEL", "xla")
        xla = DeviceTopK(X, Y, microbatch=False)
        fi, fs = fused.items_topk([2, 5], 6)
        xi, xs = xla.items_topk([2, 5], 6)
        np.testing.assert_array_equal(fi, xi)
        np.testing.assert_array_equal(fs, xs)

    def test_int8_store_fused_agrees_with_int8_xla(self, monkeypatch,
                                                   factor_pair):
        monkeypatch.setenv("PIO_SERVE_PRECISION", "int8")
        fused, xla = self._pair(monkeypatch, factor_pair)
        for uid in (0, 4, 9):
            fi, fs = fused.user_topk(uid, 6)
            xi, xs = xla.user_topk(uid, 6)
            np.testing.assert_array_equal(fi, xi)
            np.testing.assert_allclose(fs, xs, rtol=1e-5)

    def test_fused_aot_ladder_and_zero_recompile(self, monkeypatch,
                                                 factor_pair):
        """The fused programs ride the AOT ladder: warmup precompiles
        every entry and steady-state queries hit those executables (the
        serve-time-compile contract the bench asserts end to end)."""
        from predictionio_tpu.utils import metrics

        X, Y, seen = factor_pair
        monkeypatch.setenv("PIO_SERVE_KERNEL", "fused")
        srv = DeviceTopK(X, Y, seen, microbatch=False)
        stats = srv.warmup(max_k=32)
        assert stats["compiled"] > 0
        metrics.install_jit_compile_listener()
        before = metrics.JIT_COMPILES.value()
        srv.user_topk(3, 5)
        srv.users_topk(np.asarray([1, 2, 3]), 10)
        srv.items_topk([4], 8)
        assert metrics.JIT_COMPILES.value() == before

    def test_patch_users_then_fused_serves_fresh(self, monkeypatch,
                                                 factor_pair):
        fused, xla = self._pair(monkeypatch, factor_pair)
        rng = np.random.default_rng(31)
        fresh = int_factors(rng, (2, 6))
        for srv in (fused, xla):
            srv.patch_users(np.asarray([1, 22]), fresh,
                            seen_items={1: np.asarray([0, 2]),
                                        22: np.asarray([5])})
        for uid in (1, 22):
            fi, fs = fused.user_topk(uid, 5)
            xi, xs = xla.user_topk(uid, 5)
            np.testing.assert_array_equal(fi, xi)
            np.testing.assert_array_equal(fs, xs)

    def test_opt_out_env(self, monkeypatch, factor_pair):
        X, Y, seen = factor_pair
        monkeypatch.setenv("PIO_SERVE_KERNEL", "xla")
        srv = DeviceTopK(X, Y, seen)
        assert srv._kernel == "xla"
        monkeypatch.setenv("PIO_SERVE_KERNEL", "bogus")
        with pytest.raises(ValueError, match="PIO_SERVE_KERNEL"):
            DeviceTopK(X, Y, seen)

    @pytest.mark.slow
    def test_large_shape_multi_tile(self, monkeypatch):
        """A multi-tile catalog with a big k bucket (heavier interpret
        run, slow-marked; `pytest -m pallas` on the bench host covers
        it)."""
        rng = np.random.default_rng(40)
        Q = int_factors(rng, (16, 16))
        Y = int_factors(rng, (1000, 16))
        sc = rng.integers(0, 1000, (12, 16)).astype(np.int32)
        sm = np.ones((12, 16), dtype=np.float32)
        vals, idx = fused_gather_score_topk(
            jnp.asarray(Q), jnp.asarray(Y), sc, sm, k=64,
            n_items=997, mask_seen=True, interpret=True)
        wv, wi = xla_chain_topk(Q, Y, sc, sm, 64, 997)
        fin = np.isfinite(wv)
        np.testing.assert_array_equal(np.asarray(idx)[fin], wi[fin])
        np.testing.assert_array_equal(np.asarray(vals)[fin], wv[fin])
